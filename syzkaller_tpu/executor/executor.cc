// syz-executor (TPU build) — in-VM program interpreter.
//
// Role parity with reference /root/reference/executor/executor.h:151-299 and
// executor_linux.cc:46-306, redesigned rather than translated:
//
//  * The syscall table is NOT compiled in (the reference generates 10.8k lines
//    of per-OS headers, executor/syscalls_linux.h). Instead the fuzzer streams
//    the call-id -> syscall-NR table through shared memory at handshake time,
//    so one binary serves any description revision. This matters for the TPU
//    build: the Python description compiler is the single source of truth and
//    the device tables and executor table can never skew.
//  * Control protocol: fixed 48-byte little-endian u64 request frames on
//    stdin, 24-byte replies on stdout (the reference uses magic status bytes
//    67/68/69, pkg/ipc/ipc_linux.go:309-...). Program input and result output
//    travel through two mmap'd files exactly like the reference (2MB in /
//    16MB out, pkg/ipc/ipc.go:36).
//  * Coverage: per-thread KCOV (KCOV_ENABLE/KCOV_DISABLE ioctls, reference
//    executor_linux.cc:262-306) with edge signal sig = pc ^ hash(prev) and an
//    open-addressing dedup table (reference executor.h:388-401,497-527).
//    Where KCOV is unavailable (containers, non-Linux dev hosts) a
//    deterministic synthetic signal derived from (nr, errno) keeps the whole
//    fuzzing loop runnable hermetically — the reference has no such fallback
//    (SURVEY.md §4 flags that gap).
//  * Threaded + collide execution: each call runs on a worker thread with a
//    bounded completion wait; collide mode re-issues adjacent call pairs
//    concurrently without waiting to provoke kernel races (reference
//    executor.h:259-298).
//  * Fork server: one child per program, private cwd, process-group kill on
//    timeout (reference executor_linux.cc:144-...).
//
// Exec input format: see syzkaller_tpu/prog/encodingexec.py (byte-compatible
// with reference prog/encodingexec.go:14-288).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <sched.h>
#include <setjmp.h>
#include <signal.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <arpa/inet.h>
#include <linux/if.h>
#include <linux/if_tun.h>
#include <linux/kvm.h>
#include <net/if_arp.h>
#include <netinet/in.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/mount.h>
#include <sys/socket.h>
#include <sys/prctl.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

typedef uint64_t uint64;
typedef uint32_t uint32;
typedef uint16_t uint16;
typedef uint8_t uint8;

// ---------------- protocol constants (mirrored in ipc/protocol.py) ---------

const uint64 kReqMagic = 0x73797A74707500AAull;
const uint64 kReplyMagic = 0x73797A74707500BBull;

const uint64 kCmdHandshake = 1;
const uint64 kCmdExec = 2;
const uint64 kCmdQuit = 3;

// env flags (handshake req.flags)
const uint64 kEnvDebug = 1 << 0;
const uint64 kEnvUseKcov = 1 << 1;
const uint64 kEnvSandboxSetuid = 1 << 2;
const uint64 kEnvSandboxNamespace = 1 << 3;
const uint64 kEnvSyntheticCover = 1 << 4;
const uint64 kEnvPremapArena = 1 << 5;

// exec flags (exec req.exec_flags low 32 bits; fault call/nth in high bits)
const uint64 kExecCollectSignal = 1 << 0;
const uint64 kExecCollectCover = 1 << 1;
const uint64 kExecDedupCover = 1 << 2;
const uint64 kExecThreaded = 1 << 3;
const uint64 kExecCollide = 1 << 4;
const uint64 kExecCollectComps = 1 << 5;
const uint64 kExecInjectFault = 1 << 6;

const uint64 kStatusOk = 0;
const uint64 kStatusFailed = 1;
const uint64 kStatusHanged = 2;

// exec stream markers (prog/encodingexec.py)
const uint64 kInstrEof = ~0ull;
const uint64 kInstrCopyin = ~0ull - 1;
const uint64 kInstrCopyout = ~0ull - 2;
const uint64 kArgConst = 0;
const uint64 kArgResult = 1;
const uint64 kArgData = 2;
const uint64 kArgCsum = 3;
const uint64 kCsumChunkData = 0;
const uint64 kCsumChunkConst = 1;

const uint64 kPseudoNrBase = 1ull << 30;  // descriptions/compiler.py:58

// call record flags
const uint32 kCallExecuted = 1 << 0;
const uint32 kCallFaultInjected = 1 << 1;

const int kMaxThreads = 16;
const int kMaxInstr = 16 << 10;
const int kMaxArgs = 6;
const int kCallWaitMs = 20;       // reference executor.h:268
const int kFinalWaitMs = 100;
const int kCoverSize = 64 << 10;
const int kDedupTableSize = 8 << 10;

// kcov ioctls (reference executor_linux.cc:27-40)
#define KCOV_INIT_TRACE _IOR('c', 1, unsigned long)
#define KCOV_ENABLE _IO('c', 100)
#define KCOV_DISABLE _IO('c', 101)
#define KCOV_TRACE_PC 0
#define KCOV_TRACE_CMP 1

struct req_t {
  uint64 magic, cmd, flags, pid, exec_flags, timeout_ms;
};
struct reply_t {
  uint64 magic, status, exec_ns;
};

// ---------------- globals -------------------------------------------------

static bool flag_debug;
static bool flag_kcov;
static bool flag_synthetic;
static bool flag_premap;
static uint64 flag_sandbox;

static char* in_mem;
static char* out_mem;
static size_t in_size, out_size;

static uint64 g_ncalls_table;      // syscall table from handshake
static uint64* g_nr_table;
static uint64 g_page_size = 4096;
static uint64 g_num_pages = 4096;
static uint64 g_data_offset = 0x10000000;

static int g_pid;
static bool collect_signal, collect_cover, dedup_cover, collect_comps;
static bool flag_threaded, flag_collide;
static int fault_call = -1, fault_nth;

static __thread sigjmp_buf nonfail_jmp;
static __thread int nonfail_active;

static void debug(const char* msg, ...) {
  if (!flag_debug) return;
  va_list args;
  va_start(args, msg);
  vfprintf(stderr, msg, args);
  va_end(args);
  fflush(stderr);
}

[[noreturn]] static void fail(const char* msg) {
  fprintf(stderr, "executor: %s (errno %d: %s)\n", msg, errno,
          strerror(errno));
  _exit(67);
}

static uint64 now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64)ts.tv_sec * 1000000000ull + ts.tv_nsec;
}

// ---------------- NONFAILING memory access --------------------------------
// Tolerates copyin/copyout on unmapped addresses the same way the reference
// runtime does with setjmp+SIGSEGV (reference executor/common_linux.h
// NONFAILING); mutation can aim pointers anywhere.

static void segv_handler(int sig, siginfo_t*, void*) {
  if (nonfail_active) siglongjmp(nonfail_jmp, 1);
  _exit(128 + sig);
}

static void install_segv_handler() {
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = segv_handler;
  sa.sa_flags = SA_SIGINFO | SA_NODEFER;
  sigaction(SIGSEGV, &sa, nullptr);
  sigaction(SIGBUS, &sa, nullptr);
}

#define NONFAILING(...)                      \
  do {                                       \
    nonfail_active = 1;                      \
    if (!sigsetjmp(nonfail_jmp, 1)) {        \
      __VA_ARGS__;                           \
    }                                        \
    nonfail_active = 0;                      \
  } while (0)

// ---------------- coverage ------------------------------------------------

static inline uint32 hash32(uint32 x) {
  x ^= x >> 16;
  x *= 0x85ebca6b;
  x ^= x >> 13;
  x *= 0xc2b2ae35;
  x ^= x >> 16;
  return x;
}

struct cover_t {
  int fd = -1;
  uint64* data = nullptr;   // data[0] = count, then pcs
  bool usable = false;
};

static bool kcov_open(cover_t* cov) {
  cov->fd = open("/sys/kernel/debug/kcov", O_RDWR);
  if (cov->fd == -1) return false;
  if (ioctl(cov->fd, KCOV_INIT_TRACE, kCoverSize)) {
    close(cov->fd);
    cov->fd = -1;
    return false;
  }
  cov->data = (uint64*)mmap(nullptr, kCoverSize * sizeof(uint64),
                            PROT_READ | PROT_WRITE, MAP_SHARED, cov->fd, 0);
  if (cov->data == MAP_FAILED) {
    close(cov->fd);
    cov->fd = -1;
    cov->data = nullptr;
    return false;
  }
  cov->usable = true;
  return true;
}

static void kcov_enable(cover_t* cov, bool comps) {
  if (!cov->usable) return;
  ioctl(cov->fd, KCOV_ENABLE, comps ? KCOV_TRACE_CMP : KCOV_TRACE_PC);
  __atomic_store_n(&cov->data[0], 0, __ATOMIC_RELAXED);
}

static void kcov_reset(cover_t* cov) {
  if (cov->usable) __atomic_store_n(&cov->data[0], 0, __ATOMIC_RELAXED);
}

// ---------------- output region -------------------------------------------
// Layout (u32 LE): [0]=completed call count; then per call:
//   index num errno flags nsig ncover ncomps  sig[nsig] cover[ncover]
//   comps[2*ncomps as u64 pairs -> 4*ncomps u32]
// The count at [0] is bumped only after the record is fully written, so a
// killed child leaves a consistent prefix (reference executor.h:336-428).

static uint32* out_pos;

static void out_reset() {
  ((uint32*)out_mem)[0] = 0;
  out_pos = (uint32*)out_mem + 1;
}

static inline bool out_fits(size_t nwords) {
  return (char*)(out_pos + nwords) <= out_mem + out_size;
}

// ---------------- threads -------------------------------------------------

struct thread_t {
  int id = 0;
  bool created = false;
  pthread_t th;
  pthread_mutex_t mu = PTHREAD_MUTEX_INITIALIZER;
  pthread_cond_t cv = PTHREAD_COND_INITIALIZER;
  int state = 0;  // 0 idle, 1 pending, 2 running, 3 done
  bool quit = false;

  // call payload
  int call_index = 0;     // position in program
  int call_num = 0;       // dense call id
  uint64 nr = 0;
  uint64 args[kMaxArgs] = {};
  int copyout_index = -1;  // instruction index of the call itself
  bool do_fault = false;
  int fault_nth_local = 0;

  // result
  uint64 ret = 0;
  int err = 0;
  bool executed = false;
  bool fault_injected = false;
  bool collect = true;     // write an output record for this execution

  cover_t cov;
};

static thread_t threads[kMaxThreads];

struct result_t {
  bool valid = false;
  uint64 val = 0;
};
static result_t results[kMaxInstr];

static bool fault_injection_enter(thread_t* th) {
  if (!th->do_fault) return false;
  int fd = open("/proc/thread-self/fail-nth", O_RDWR);
  if (fd == -1) return false;
  char buf[16];
  int n = snprintf(buf, sizeof(buf), "%d", th->fault_nth_local + 1);
  ssize_t w = write(fd, buf, n);
  (void)w;
  close(fd);
  return true;
}

static bool fault_injection_check(thread_t* th) {
  if (!th->do_fault) return false;
  int fd = open("/proc/thread-self/fail-nth", O_RDONLY);
  if (fd == -1) return false;
  char buf[16] = {};
  ssize_t r = read(fd, buf, sizeof(buf) - 1);
  close(fd);
  return r > 0 && atoi(buf) == 0;
}

// ---------------- pseudo-syscalls (syz_*) ---------------------------------
// Fixed ids mirrored from descriptions/compiler.py PSEUDO_IDS; role parity
// with reference executor/common_linux.h:298-660 (TUN + pseudo-syscalls)
// and common_kvm_amd64.h (KVM vcpu setup) — reimplemented from the
// documented kernel APIs, not translated.

const uint64 kSyzOpenDev = 0;
const uint64 kSyzOpenPts = 1;
const uint64 kSyzEmitEthernet = 2;
const uint64 kSyzExtractTcpRes = 3;
const uint64 kSyzFuseMount = 4;
const uint64 kSyzFusectlMount = 5;
const uint64 kSyzKvmSetupCpu = 6;
const uint64 kSyzTest = 7;

// --- virtual NIC (reference initialize_tun common_linux.h:298-360) ---

static int g_tun_fd = -1;

static void setup_tun(int pid) {
  // tap device per proc; packets written to the fd enter the kernel
  // network stack as if received on the wire
  g_tun_fd = open("/dev/net/tun", O_RDWR | O_NONBLOCK);
  if (g_tun_fd == -1) return;
  struct ifreq ifr;
  memset(&ifr, 0, sizeof(ifr));
  snprintf(ifr.ifr_name, sizeof(ifr.ifr_name), "syz%d", pid);
  ifr.ifr_flags = IFF_TAP | IFF_NO_PI;
  if (ioctl(g_tun_fd, TUNSETIFF, &ifr) < 0) {
    close(g_tun_fd);
    g_tun_fd = -1;
    return;
  }
  int sk = socket(AF_INET, SOCK_DGRAM, 0);
  if (sk < 0) return;
  // 172.20.<pid>.1/24, up
  struct sockaddr_in* sin = (struct sockaddr_in*)&ifr.ifr_addr;
  sin->sin_family = AF_INET;
  sin->sin_addr.s_addr = htonl(0xac140001 | ((uint32)pid << 8));
  ioctl(sk, SIOCSIFADDR, &ifr);
  ifr.ifr_flags = IFF_UP;
  ioctl(sk, SIOCSIFFLAGS, &ifr);
  close(sk);
}

static uint64 syz_emit_ethernet(uint64* a, int* err) {
  // a0 = len, a1 = packet ptr
  if (g_tun_fd == -1) {
    *err = EBADFD;
    return (uint64)-1;
  }
  uint64 len = a[0];
  if (len > (64 << 10)) len = 64 << 10;
  long n = -1;
  NONFAILING(n = write(g_tun_fd, (void*)a[1], len));
  if (n == -1) *err = errno;
  return (uint64)n;
}

static uint64 syz_extract_tcp_res(uint64* a, int* err) {
  // a0 = res ptr {seq, ack}, a1 = seq_inc, a2 = ack_inc: read one packet
  // off the tap and record its TCP seq/ack (+increments) for reuse
  if (g_tun_fd == -1) {
    *err = EBADFD;
    return (uint64)-1;
  }
  char pkt[1 << 12];
  long n = read(g_tun_fd, pkt, sizeof(pkt));
  if (n < (long)(14 + 20 + 20)) {
    *err = n < 0 ? errno : EAGAIN;
    return (uint64)-1;
  }
  // eth(14) + ipv4(ihl) + tcp: seq at +4, ack at +8
  int ihl = (pkt[14] & 0xF) * 4;
  int tcp = 14 + ihl;
  if (tcp + 20 > n || ((pkt[14] >> 4) & 0xF) != 4) {
    *err = EINVAL;
    return (uint64)-1;
  }
  uint32 seq, ack;
  memcpy(&seq, pkt + tcp + 4, 4);
  memcpy(&ack, pkt + tcp + 8, 4);
  seq = __builtin_bswap32(seq) + (uint32)a[1];
  ack = __builtin_bswap32(ack) + (uint32)a[2];
  NONFAILING({
    ((uint32*)a[0])[0] = seq;
    ((uint32*)a[0])[1] = ack;
  });
  return 0;
}

static uint64 syz_open_dev(uint64* a, int* err) {
  // a0 = device path with '#' placeholder, a1 = id, a2 = flags
  char buf[128] = {};
  NONFAILING(strncpy(buf, (char*)a[0], sizeof(buf) - 1));
  for (char* p = buf; *p; p++)
    if (*p == '#') *p = '0' + (char)(a[1] % 10);
  long fd = open(buf, (int)a[2], 0);
  if (fd == -1) *err = errno;
  return (uint64)fd;
}

static uint64 syz_open_pts(uint64* a, int* err) {
  // a0 = ptmx fd, a1 = flags: open the slave end
  int ptyno = 0;
  if (ioctl((int)a[0], TIOCGPTN, &ptyno)) {
    *err = errno;
    return (uint64)-1;
  }
  char buf[32];
  snprintf(buf, sizeof(buf), "/dev/pts/%d", ptyno);
  long fd = open(buf, (int)a[1], 0);
  if (fd == -1) *err = errno;
  return (uint64)fd;
}

static uint64 syz_fuse_mount(uint64* a, int* err, bool fusectl) {
  // a0 = dest path, a1 = mode, a2 = uid, a3 = gid, a4 = maxread,
  // a5 = mount flags
  uint64 mode = a[1], uid = a[2], gid = a[3], maxread = a[4];
  int fd = open("/dev/fuse", O_RDWR);
  if (fd == -1) {
    *err = errno;
    return (uint64)-1;
  }
  char opts[256];
  int n = snprintf(opts, sizeof(opts),
                   "fd=%d,rootmode=%o,user_id=%llu,group_id=%llu",
                   fd, (unsigned)(mode & ~3u), (unsigned long long)uid,
                   (unsigned long long)gid);
  if (maxread)
    snprintf(opts + n, sizeof(opts) - n, ",max_read=%llu",
             (unsigned long long)maxread);
  const char* fstype = (mode & 1) ? "fuseblk" : "fuse";
  char dest[128] = {};
  NONFAILING(strncpy(dest, (char*)a[0], sizeof(dest) - 1));
  mkdir(dest, 0777);
  long res = mount("/dev/fuse", dest, fstype, (unsigned long)a[5], opts);
  if (res == -1) {
    *err = errno;
    close(fd);
    return (uint64)-1;
  }
  if (fusectl) {
    // also expose the fuse control fs (reference syz_fusectl_mount)
    mkdir("./fusectl", 0777);
    mount("fusectl", "./fusectl", "fusectl", 0, 0);
  }
  return (uint64)fd;
}

// --- KVM vcpu setup (reference common_kvm_amd64.h's role) ---

#if defined(__x86_64__)
static void kvm_setup_long_mode(void* mem, struct kvm_sregs* sregs) {
  // identity-map the first 1GB with one PDPT 1GB page; tables at guest
  // phys 0x2000/0x3000 (inside the usermem arena)
  uint64* pml4 = (uint64*)((char*)mem + 0x2000);
  uint64* pdpt = (uint64*)((char*)mem + 0x3000);
  pml4[0] = 0x3000 | 3;            // present|write -> pdpt
  pdpt[0] = 0x83;                  // present|write|1GB page @0
  sregs->cr3 = 0x2000;
  sregs->cr4 |= 1 << 5;            // PAE
  sregs->cr0 |= (1u << 0) | (1u << 31);  // PE | PG
  sregs->efer |= (1 << 8) | (1 << 10);   // LME | LMA
  struct kvm_segment seg;
  memset(&seg, 0, sizeof(seg));
  seg.base = 0;
  seg.limit = 0xffffffff;
  seg.selector = 0x8;
  seg.present = 1;
  seg.type = 11;  // exec/read accessed
  seg.dpl = 0;
  seg.db = 0;
  seg.s = 1;
  seg.l = 1;  // 64-bit
  seg.g = 1;
  sregs->cs = seg;
  seg.type = 3;  // data
  seg.selector = 0x10;
  seg.l = 0;
  sregs->ds = sregs->es = sregs->ss = seg;
}

static uint64 syz_kvm_setup_cpu(uint64* a, int* err) {
  // a0 = vm fd, a1 = vcpu fd, a2 = usermem (>= 24 pages), a3 = text ptr,
  // a4 = text len, a5 = flags (bit0: long mode, else real mode)
  int vmfd = (int)a[0], cpufd = (int)a[1];
  void* mem = (void*)a[2];
  uint64 flags = a[5];
  const uint64 mem_size = 24 * 4096;

  struct kvm_userspace_memory_region reg;
  memset(&reg, 0, sizeof(reg));
  reg.slot = 0;
  reg.guest_phys_addr = 0;
  reg.memory_size = mem_size;
  reg.userspace_addr = (uint64)mem;
  bool ok = false;
  NONFAILING({
    memset(mem, 0, mem_size);
    ok = true;
  });
  if (!ok || ioctl(vmfd, KVM_SET_USER_MEMORY_REGION, &reg) < 0) {
    *err = ok ? errno : EFAULT;
    return (uint64)-1;
  }

  // guest code at phys 0x1000, padded with hlt
  const uint64 code_at = 0x1000;
  uint64 tlen = a[4];
  if (tlen > 0x800) tlen = 0x800;
  NONFAILING({
    memset((char*)mem + code_at, 0xf4 /* hlt */, 0x1000);
    memcpy((char*)mem + code_at, (void*)a[3], tlen);
  });

  struct kvm_sregs sregs;
  if (ioctl(cpufd, KVM_GET_SREGS, &sregs) < 0) {
    *err = errno;
    return (uint64)-1;
  }
  if (flags & 1) {
    kvm_setup_long_mode(mem, &sregs);
  } else {
    // real mode at 0:code_at
    sregs.cs.base = 0;
    sregs.cs.selector = 0;
    sregs.cr0 &= ~1ull;  // PE off
  }
  if (ioctl(cpufd, KVM_SET_SREGS, &sregs) < 0) {
    *err = errno;
    return (uint64)-1;
  }
  struct kvm_regs regs;
  memset(&regs, 0, sizeof(regs));
  regs.rip = code_at;
  regs.rsp = mem_size - 16;
  regs.rflags = 2;  // reserved bit must be set
  if (ioctl(cpufd, KVM_SET_REGS, &regs) < 0) {
    *err = errno;
    return (uint64)-1;
  }
  return 0;
}
#else
static uint64 syz_kvm_setup_cpu(uint64* a, int* err) {
  (void)a;
  *err = ENOSYS;
  return (uint64)-1;
}
#endif

static uint64 execute_pseudo(uint64 nr, uint64* args, int* err) {
  switch (nr - kPseudoNrBase) {
    case kSyzOpenDev:
      return syz_open_dev(args, err);
    case kSyzOpenPts:
      return syz_open_pts(args, err);
    case kSyzEmitEthernet:
      return syz_emit_ethernet(args, err);
    case kSyzExtractTcpRes:
      return syz_extract_tcp_res(args, err);
    case kSyzFuseMount:
      return syz_fuse_mount(args, err, false);
    case kSyzFusectlMount:
      return syz_fuse_mount(args, err, true);
    case kSyzKvmSetupCpu:
      return syz_kvm_setup_cpu(args, err);
    case kSyzTest:
      return 0;
  }
  *err = ENOSYS;
  return (uint64)-1;
}

// pid of the process executing the program: a program call that forks
// (clone/clone3/fork in the corpus) must not let the child continue the
// program loop, or two processes race writing output records.
static pid_t program_pid;

static void execute_call(thread_t* th) {
  if (flag_kcov) kcov_reset(&th->cov);
  bool faulted = fault_injection_enter(th);
  long tid_before = syscall(SYS_gettid);
  errno = 0;
  uint64 ret;
  int err = 0;
  if (th->nr >= kPseudoNrBase) {
    ret = execute_pseudo(th->nr, th->args, &err);
  } else {
    ret = (uint64)syscall(th->nr, th->args[0], th->args[1], th->args[2],
                          th->args[3], th->args[4], th->args[5]);
    err = (ret == (uint64)-1) ? errno : 0;
  }
  // A forked child process resumes here too; so does a raw
  // clone3(CLONE_THREAD, stack=0) thread (same pid, new tid, parent's
  // sp). Neither may continue the program loop or they race the real
  // thread on syscalls and output records.
  if (program_pid && getpid() != program_pid) _exit(0);
  if (syscall(SYS_gettid) != tid_before) syscall(SYS_exit, 0);
  th->ret = ret;
  th->err = err;
  th->executed = true;
  th->fault_injected = faulted && fault_injection_check(th);
}

static void* worker(void* arg) {
  thread_t* th = (thread_t*)arg;
  install_segv_handler();  // handlers are per-process but jmpbuf is per-thread
  if (flag_kcov) {
    kcov_open(&th->cov);
    kcov_enable(&th->cov, collect_comps);
  }
  pthread_mutex_lock(&th->mu);
  for (;;) {
    while (th->state != 1 && !th->quit)
      pthread_cond_wait(&th->cv, &th->mu);
    if (th->quit) break;
    th->state = 2;
    pthread_mutex_unlock(&th->mu);
    execute_call(th);
    pthread_mutex_lock(&th->mu);
    th->state = 3;
    pthread_cond_broadcast(&th->cv);
  }
  pthread_mutex_unlock(&th->mu);
  return nullptr;
}

static void thread_start(thread_t* th) {
  if (th->created) return;
  th->created = true;
  pthread_attr_t attr;
  pthread_attr_init(&attr);
  pthread_attr_setstacksize(&attr, 128 << 10);
  if (pthread_create(&th->th, &attr, worker, th)) fail("pthread_create");
  pthread_attr_destroy(&attr);
}

static void schedule_call(thread_t* th) {
  pthread_mutex_lock(&th->mu);
  th->state = 1;
  pthread_cond_signal(&th->cv);
  pthread_mutex_unlock(&th->mu);
}

// Returns true if the call completed within timeout_ms.
static bool wait_call(thread_t* th, int timeout_ms) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_nsec += (long)timeout_ms * 1000000;
  ts.tv_sec += ts.tv_nsec / 1000000000;
  ts.tv_nsec %= 1000000000;
  pthread_mutex_lock(&th->mu);
  while (th->state != 3) {
    if (pthread_cond_timedwait(&th->cv, &th->mu, &ts)) break;
  }
  bool done = th->state == 3;
  pthread_mutex_unlock(&th->mu);
  return done;
}

// ---------------- signal extraction ---------------------------------------

static uint32 dedup_table[kDedupTableSize];

static bool dedup(uint32 sig) {
  for (int i = 0; i < 4; i++) {
    uint32 pos = (sig + i) % kDedupTableSize;
    if (dedup_table[pos] == sig) return true;
    if (dedup_table[pos] == 0) {
      dedup_table[pos] = sig;
      return false;
    }
  }
  return false;
}

// Writes one output record for a completed call (reference handle_completion,
// executor.h:336-428).
static void write_completion(thread_t* th) {
  if (!th->collect) return;
  if (!out_fits(7)) return;
  uint32* rec = out_pos;
  rec[0] = (uint32)th->call_index;
  rec[1] = (uint32)th->call_num;
  rec[2] = (uint32)th->err;
  rec[3] = (th->executed ? kCallExecuted : 0) |
           (th->fault_injected ? kCallFaultInjected : 0);
  uint32 *nsig = &rec[4], *ncover = &rec[5], *ncomps = &rec[6];
  *nsig = *ncover = *ncomps = 0;
  out_pos = rec + 7;

  if (flag_kcov && th->cov.usable && !collect_comps) {
    uint64 n = __atomic_load_n(&th->cov.data[0], __ATOMIC_RELAXED);
    if (n > kCoverSize - 1) n = kCoverSize - 1;
    if (collect_signal) {
      memset(dedup_table, 0, sizeof(dedup_table));
      uint32 prev = 0;
      for (uint64 i = 0; i < n && out_fits(1); i++) {
        uint32 pc = (uint32)th->cov.data[i + 1];
        uint32 sig = pc ^ (hash32(prev) & 0xfffff);
        prev = pc;
        if (dedup(sig)) continue;
        *out_pos++ = sig;
        (*nsig)++;
      }
    }
    if (collect_cover) {
      uint32 last = 0;
      for (uint64 i = 0; i < n && out_fits(1); i++) {
        uint32 pc = (uint32)th->cov.data[i + 1];
        if (dedup_cover && pc == last) continue;
        last = pc;
        *out_pos++ = pc;
        (*ncover)++;
      }
    }
  } else if (flag_kcov && th->cov.usable && collect_comps) {
    // KCOV_TRACE_CMP records: type, arg1, arg2, pc (4 u64 each)
    uint64 n = __atomic_load_n(&th->cov.data[0], __ATOMIC_RELAXED);
    for (uint64 i = 0; i < n && out_fits(4); i++) {
      uint64* rec64 = &th->cov.data[1 + 4 * i];
      memcpy(out_pos, &rec64[1], 8);
      memcpy(out_pos + 2, &rec64[2], 8);
      out_pos += 4;
      (*ncomps)++;
    }
  } else if (flag_synthetic && (collect_signal || collect_cover)) {
    // Deterministic fallback signal: two edges per (nr, errno) outcome.
    // Keeps generation->exec->triage runnable with no KCOV (containers, CI).
    uint32 s0 = hash32((uint32)th->nr * 2654435761u);
    uint32 s1 = hash32(s0 ^ (uint32)th->err);
    if (collect_signal && out_fits(2)) {
      *out_pos++ = s0;
      *out_pos++ = s1;
      *nsig = 2;
    }
    if (collect_cover && out_fits(2)) {
      *out_pos++ = s0;
      *out_pos++ = s1;
      *ncover = 2;
    }
  }
  // commit
  uint32* hdr = (uint32*)out_mem;
  __atomic_store_n(hdr, hdr[0] + 1, __ATOMIC_RELEASE);
}

// ---------------- exec stream interpreter ---------------------------------

struct parser_t {
  uint64* words;
  size_t nwords;
  size_t pos = 0;
  bool ok = true;

  uint64 next() {
    if (pos >= nwords) {
      ok = false;
      return kInstrEof;
    }
    return words[pos++];
  }
  uint64 peek() { return pos < nwords ? words[pos] : kInstrEof; }
};

// Reads one encoded arg; returns its value (for call args); for copyin,
// writes to addr instead when addr != 0.
static uint64 read_arg(parser_t* p, uint64 copyin_addr) {
  uint64 kind = p->next();
  switch (kind) {
    case kArgConst: {
      uint64 size = p->next();
      uint64 val = p->next();
      uint64 bf_off = p->next();
      uint64 bf_len = p->next();
      if (copyin_addr) {
        NONFAILING({
          char* a = (char*)copyin_addr;
          if (bf_off == 0 && bf_len == 0) {
            memcpy(a, &val, size > 8 ? 8 : size);
          } else {
            uint64 cur = 0;
            memcpy(&cur, a, size > 8 ? 8 : size);
            uint64 mask = ((bf_len < 64 ? (1ull << bf_len) : 0ull) - 1)
                          << bf_off;
            cur = (cur & ~mask) | ((val << bf_off) & mask);
            memcpy(a, &cur, size > 8 ? 8 : size);
          }
        });
      }
      return val;
    }
    case kArgResult: {
      uint64 size = p->next();
      (void)size;
      uint64 idx = p->next();
      uint64 op_div = p->next();
      uint64 op_add = p->next();
      uint64 val = 0;
      if (idx < kMaxInstr && results[idx].valid) val = results[idx].val;
      if (op_div) val /= op_div;
      val += op_add;
      if (copyin_addr)
        NONFAILING(memcpy((char*)copyin_addr, &val, size > 8 ? 8 : size));
      return val;
    }
    case kArgData: {
      uint64 size = p->next();
      char* src = (char*)&p->words[p->pos];
      p->pos += (size + 7) / 8;
      if (copyin_addr) {
        NONFAILING(memcpy((char*)copyin_addr, src, size));
        return 0;
      }
      // Data as a direct syscall arg: pass a pointer to a scratch copy.
      static __thread char scratch[4096];
      uint64 n = size < sizeof(scratch) ? size : sizeof(scratch);
      memcpy(scratch, src, n);
      return (uint64)scratch;
    }
    case kArgCsum: {
      // Ones'-complement internet checksum over a chunk list (data ranges
      // already copied into guest memory + pseudo-header constants), stored
      // big-endian into the csum field (prog/checksum.py emits these).
      uint64 size = p->next();
      p->next();  // csum kind: only inet accumulation exists on the wire
      uint64 nchunks = p->next();
      uint32 acc = 0;
      for (uint64 i = 0; i < nchunks; i++) {
        uint64 chunk_kind = p->next();
        uint64 value = p->next();
        uint64 chunk_size = p->next();
        if (chunk_kind == kCsumChunkConst) {
          // 4-byte consts (IPv6 pseudo-header length/next-header words)
          // sum as two big-endian 16-bit words; 2-byte consts as one.
          if (chunk_size == 4)
            acc += (uint32)((value >> 16) & 0xffff);
          acc += (uint32)(value & 0xffff);
        } else {
          NONFAILING({
            const uint8* d = (const uint8*)value;
            for (uint64 j = 0; j + 1 < chunk_size; j += 2)
              acc += ((uint32)d[j] << 8) | d[j + 1];
            if (chunk_size & 1) acc += (uint32)d[chunk_size - 1] << 8;
          });
        }
        while (acc >> 16) acc = (acc & 0xffff) + (acc >> 16);
      }
      uint16 csum = (uint16)~acc;
      if (copyin_addr) {
        NONFAILING({
          char* a = (char*)copyin_addr;
          a[0] = (char)(csum >> 8);
          if (size >= 2) a[1] = (char)(csum & 0xff);
        });
      }
      return csum;
    }
    default:
      p->ok = false;
      return 0;
  }
}

static void execute_one() {
  program_pid = getpid();
  memset(results, 0, sizeof(results));
  out_reset();

  parser_t p;
  p.words = (uint64*)in_mem;
  p.nwords = in_size / 8;

  for (int pass = 0; pass < (flag_collide ? 2 : 1); pass++) {
    bool colliding = pass == 1;
    p.pos = 0;
    uint64 instr_idx = 0;
    int call_seq = 0;  // ordinal of the call within the program
    int next_thread = 0;
    thread_t* pair[2] = {nullptr, nullptr};
    int pair_n = 0;

    for (;;) {
      uint64 w = p.peek();
      if (!p.ok || w == kInstrEof) break;
      if (w == kInstrCopyin) {
        p.next();
        uint64 addr = p.next();
        read_arg(&p, addr);
        instr_idx++;
        continue;
      }
      if (w == kInstrCopyout) {
        p.next();
        uint64 addr = p.next();
        uint64 size = p.next();
        uint64 val = 0;
        bool got = false;
        NONFAILING({
          memcpy(&val, (char*)addr, size > 8 ? 8 : size);
          got = true;
        });
        if (!colliding && got && instr_idx < kMaxInstr) {
          results[instr_idx].valid = true;
          results[instr_idx].val = val;
        }
        instr_idx++;
        continue;
      }
      // a syscall
      uint64 call_id = p.next();
      uint64 nargs = p.next();
      uint64 args[kMaxArgs] = {};
      for (uint64 i = 0; i < nargs; i++) {
        uint64 v = read_arg(&p, 0);
        if (i < kMaxArgs) args[i] = v;
      }
      uint64 nr = call_id < g_ncalls_table ? g_nr_table[call_id] : call_id;
      int call_index = call_seq++;

      if (!flag_threaded && !colliding) {
        // serial inline execution on the main thread
        thread_t* th = &threads[0];
        th->call_index = call_index;
        th->call_num = (int)call_id;
        th->nr = nr;
        memcpy(th->args, args, sizeof(args));
        th->do_fault = fault_call == call_index && fault_nth >= 0;
        th->fault_nth_local = fault_nth;
        th->collect = true;
        if (flag_kcov && !th->cov.usable && th->cov.fd == -1) kcov_open(&th->cov),
            kcov_enable(&th->cov, collect_comps);
        execute_call(th);
        if (instr_idx < kMaxInstr) {
          results[instr_idx].valid = true;
          results[instr_idx].val = th->ret;
        }
        write_completion(th);
      } else {
        thread_t* th = &threads[next_thread % kMaxThreads];
        next_thread++;
        thread_start(th);
        if (!wait_call(th, 0) && th->state != 0) {
          // thread still busy from an earlier call; skip scheduling onto it
          // (its eventual completion is not collected)
        }
        if (th->state == 0 || th->state == 3) {
          th->state = 0;
          th->call_index = call_index;
          th->call_num = (int)call_id;
          th->nr = nr;
          memcpy(th->args, args, sizeof(args));
          th->do_fault = !colliding && fault_call == call_index;
          th->fault_nth_local = fault_nth;
          th->collect = !colliding;
          schedule_call(th);
          if (!colliding) {
            if (wait_call(th, kCallWaitMs)) {
              if (instr_idx < kMaxInstr) {
                results[instr_idx].valid = true;
                results[instr_idx].val = th->ret;
              }
              write_completion(th);
              th->state = 0;
            }
          } else {
            // collide mode: issue pairs concurrently, wait only per pair
            pair[pair_n++ % 2] = th;
            if (pair_n % 2 == 0) {
              wait_call(pair[0], kCallWaitMs);
              wait_call(pair[1], kCallWaitMs);
              if (pair[0]->state == 3) pair[0]->state = 0;
              if (pair[1]->state == 3) pair[1]->state = 0;
            }
          }
        }
      }
      instr_idx++;
    }
    if (colliding && pair_n % 2 == 1 && pair[0]) {
      wait_call(pair[0], kCallWaitMs);
      if (pair[0]->state == 3) pair[0]->state = 0;
    }
    // grace period for stragglers, collect late completions
    if (flag_threaded && !colliding) {
      for (int i = 0; i < kMaxThreads; i++) {
        thread_t* th = &threads[i];
        if (th->created && th->state != 0 && wait_call(th, kFinalWaitMs)) {
          write_completion(th);
          th->state = 0;
        }
      }
    }
  }
}

// ---------------- sandbox -------------------------------------------------

static void sandbox_common() {
  prctl(PR_SET_PDEATHSIG, SIGKILL);
  setpgid(0, 0);
  struct rlimit rlim;
  rlim.rlim_cur = rlim.rlim_max = 8 << 20;
  setrlimit(RLIMIT_FSIZE, &rlim);
  rlim.rlim_cur = rlim.rlim_max = 256;
  setrlimit(RLIMIT_NOFILE, &rlim);
}

static void do_sandbox(uint64 kind) {
  // reference common_linux.h:686-880 (none / setuid / namespace)
  sandbox_common();
  if (kind == kEnvSandboxNamespace) {
    // best-effort user+mount+net namespace isolation
    if (unshare(CLONE_NEWUSER | CLONE_NEWNS | CLONE_NEWNET) == -1)
      debug("unshare failed: %d\n", errno);
  } else if (kind == kEnvSandboxSetuid) {
    setup_tun(g_pid);
    if (setresgid(65534, 65534, 65534) == -1) debug("setresgid failed\n");
    if (setresuid(65534, 65534, 65534) == -1) debug("setresuid failed\n");
    return;
  }
  // all sandboxes (incl. "none") get the virtual NIC, like the reference's
  // initialize_tun running for every sandbox variant
  setup_tun(g_pid);
}

// ---------------- fork server ---------------------------------------------

static void reply(uint64 status, uint64 exec_ns) {
  reply_t r = {kReplyMagic, status, exec_ns};
  if (write(STDOUT_FILENO, &r, sizeof(r)) != sizeof(r)) fail("reply write");
}

static int run_child(const req_t* req) {
  // fresh private cwd per program (reference executor_linux.cc loop())
  char dir[64];
  snprintf(dir, sizeof(dir), "./syzexec-%d-%llu", g_pid,
           (unsigned long long)now_ns());
  if (mkdir(dir, 0777) == 0) {
    if (chdir(dir)) debug("chdir failed\n");
  }
  install_segv_handler();
  do_sandbox(flag_sandbox);
  if (flag_premap) {
    // map the whole data arena so programs need no leading mmap calls
    void* want = (void*)g_data_offset;
    void* got = mmap(want, g_num_pages * g_page_size,
                     PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED_NOREPLACE, -1, 0);
    if (got != want) debug("arena premap failed\n");
  }
  execute_one();
  return 0;
}

static void handle_exec(const req_t* req) {
  uint64 ef = req->exec_flags;
  collect_signal = ef & kExecCollectSignal;
  collect_cover = ef & kExecCollectCover;
  dedup_cover = ef & kExecDedupCover;
  flag_threaded = ef & kExecThreaded;
  flag_collide = ef & kExecCollide;
  collect_comps = ef & kExecCollectComps;
  if (ef & kExecInjectFault) {
    fault_call = (int)((ef >> 32) & 0xffff);
    fault_nth = (int)((ef >> 48) & 0xffff);
  } else {
    fault_call = -1;
    fault_nth = 0;
  }
  out_reset();

  uint64 t0 = now_ns();
  pid_t child = fork();
  if (child == -1) {
    reply(kStatusFailed, 0);
    return;
  }
  if (child == 0) {
    _exit(run_child(req));
  }
  uint64 timeout_ms = req->timeout_ms ? req->timeout_ms : 5000;
  uint64 deadline = t0 + timeout_ms * 1000000ull;
  int status = 0;
  bool done = false, hanged = false;
  for (;;) {
    pid_t r = waitpid(child, &status, WNOHANG);
    if (r == child) {
      done = true;
      break;
    }
    if (now_ns() > deadline) {
      hanged = true;
      kill(-child, SIGKILL);
      kill(child, SIGKILL);
      waitpid(child, &status, 0);
      break;
    }
    usleep(500);
  }
  uint64 ns = now_ns() - t0;
  // A child killed by a signal or exiting nonzero is a NORMAL program end
  // (programs legitimately kill themselves: seccomp strict mode, exit(n),
  // stray SEGV outside NONFAILING) — unexecuted calls simply have no
  // records.  Only the executor's own failure convention (fail() exits
  // 67, matching the reference's magic status) reports kStatusFailed.
  if (hanged)
    reply(kStatusHanged, ns);
  else if (done && WIFEXITED(status) && WEXITSTATUS(status) == 67)
    reply(kStatusFailed, ns);
  else
    reply(kStatusOk, ns);
}

static void handle_handshake(const req_t* req) {
  flag_debug = req->flags & kEnvDebug;
  flag_kcov = req->flags & kEnvUseKcov;
  flag_synthetic = req->flags & kEnvSyntheticCover;
  flag_premap = req->flags & kEnvPremapArena;
  flag_sandbox = req->flags & (kEnvSandboxSetuid | kEnvSandboxNamespace);
  g_pid = (int)req->pid;

  // table in in-shm: [ncalls, page_size, num_pages, data_offset, nr...]
  uint64* tab = (uint64*)in_mem;
  g_ncalls_table = tab[0];
  g_page_size = tab[1];
  g_num_pages = tab[2];
  g_data_offset = tab[3];
  if (g_ncalls_table > (in_size - 32) / 8) fail("bad handshake table");
  free(g_nr_table);
  g_nr_table = (uint64*)malloc(g_ncalls_table * 8);
  memcpy(g_nr_table, tab + 4, g_ncalls_table * 8);
  debug("handshake: %llu calls, page=%llu pages=%llu arena=0x%llx\n",
        (unsigned long long)g_ncalls_table, (unsigned long long)g_page_size,
        (unsigned long long)g_num_pages, (unsigned long long)g_data_offset);
  reply(kStatusOk, 0);
}

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: executor <in_file> <out_file>\n");
    return 64;
  }
  int in_fd = open(argv[1], O_RDWR);
  int out_fd = open(argv[2], O_RDWR);
  if (in_fd == -1 || out_fd == -1) fail("open shm files");
  struct stat st;
  fstat(in_fd, &st);
  in_size = st.st_size;
  fstat(out_fd, &st);
  out_size = st.st_size;
  in_mem = (char*)mmap(nullptr, in_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                       in_fd, 0);
  out_mem = (char*)mmap(nullptr, out_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                        out_fd, 0);
  if (in_mem == MAP_FAILED || out_mem == MAP_FAILED) fail("mmap shm");
  signal(SIGPIPE, SIG_IGN);

  for (;;) {
    req_t req;
    ssize_t n = read(STDIN_FILENO, &req, sizeof(req));
    if (n == 0) break;  // parent closed the pipe
    if (n != sizeof(req) || req.magic != kReqMagic) fail("bad request");
    switch (req.cmd) {
      case kCmdHandshake:
        handle_handshake(&req);
        break;
      case kCmdExec:
        handle_exec(&req);
        break;
      case kCmdQuit:
        return 0;
      default:
        fail("unknown command");
    }
  }
  return 0;
}
