"""Kernel memory-leak detection via /sys/kernel/debug/kmemleak.

Role parity with reference /root/reference/syz-fuzzer/kmemleak.go
(+fuzzer.go:219,235-243): trigger a scan after a batch of executions,
read back leak records, clear.  The first scan's findings are ignored —
boot-time allocations dominate it (the reference does the same).
"""

from __future__ import annotations

import time
from typing import List, Optional

KMEMLEAK_PATH = "/sys/kernel/debug/kmemleak"


class Kmemleak:
    def __init__(self, path: str = KMEMLEAK_PATH):
        self.path = path
        self._first = True
        self.available = self._probe()

    def _probe(self) -> bool:
        try:
            with open(self.path, "rb"):
                return True
        except OSError:
            return False

    def scan(self, settle: float = 0.0) -> List[str]:
        """Trigger a scan; returns the list of leak records (text blocks).
        Boot-time noise from the first scan is discarded."""
        if not self.available:
            return []
        try:
            with open(self.path, "w") as f:
                f.write("scan")
            if settle:
                time.sleep(settle)
            with open(self.path, "r") as f:
                data = f.read()
            with open(self.path, "w") as f:
                f.write("clear")
        except OSError:
            self.available = False
            return []
        if self._first:
            self._first = False
            return []
        return parse_leaks(data)


def parse_leaks(data: str) -> List[str]:
    """Split a kmemleak report into per-leak blocks ('unreferenced
    object ...' headers)."""
    leaks: List[str] = []
    cur: Optional[List[str]] = None
    for line in data.splitlines():
        if line.startswith("unreferenced object"):
            if cur:
                leaks.append("\n".join(cur))
            cur = [line]
        elif cur is not None:
            cur.append(line)
    if cur:
        leaks.append("\n".join(cur))
    return leaks
