"""Atomic engine checkpoints: crash a campaign, not its state.

Reference syzkaller survives manager restarts because the corpus persists
in corpus.db and every fuzzer is disposable; our engine holds
device-resident state (the corpus arena, the max-signal bitset mirror,
the host RNG stream, queued triage/smash work, the attribution ledger)
that dies with the process.  This module gives the engine the corpus.db
property: a single ``workdir/engine.ckpt`` file written atomically and
verified end-to-end.

Wire format (little-endian):

    magic   10 bytes  b"SYZTPUCKPT"
    version u32       CKPT_VERSION (readers reject other versions)
    length  u64       payload byte count
    crc32   u32       zlib.crc32 of the payload
    payload bytes     zlib-compressed pickled state dict (numpy arrays
                      round-trip bit-identically, which the resume tests
                      pin; the mostly-zero arena tensors compress ~100x)

Writes go tmp + fsync + ``os.replace`` (+ directory fsync) so a crash
mid-write leaves the previous checkpoint intact; reads verify magic,
version, length, and CRC *before* unpickling, so one flipped byte yields
a clean ``CheckpointError`` — the engine logs it, counts it, and starts
fresh instead of crashing or loading garbage.

The payload schema is the writer's (Fuzzer.checkpoint_state /
_DevicePipeline.checkpoint_state) and evolves additively under ONE wire
version: new optional keys, old keys kept readable.  Worked example:
staged device work started as a single ``"pending"``/``"pending_ages"``
batch (the PR 5 double buffer) and is now the ``"inflight"`` list of up
to ``pipeline_depth`` slots ``{"outs": [8 arrays], "ages": ...}`` —
restore accepts either, so pre-pipeline checkpoints resume as a one-slot
ring and bit-identical resume stays pinned across the format change.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib

MAGIC = b"SYZTPUCKPT"
CKPT_VERSION = 1
_HEADER = struct.Struct("<IQI")  # version, payload length, crc32


class CheckpointError(RuntimeError):
    """Checkpoint missing, truncated, corrupt, or version-incompatible."""


def write_checkpoint(path: str, state: dict) -> int:
    """Atomically persist ``state`` to ``path``; returns payload bytes.

    tmp + fsync + rename: a reader (or a crash) never observes a partial
    file, and the previous checkpoint survives until the new one is
    durable."""
    payload = zlib.compress(pickle.dumps(state, protocol=4), 1)
    header = MAGIC + _HEADER.pack(CKPT_VERSION, len(payload),
                                  zlib.crc32(payload))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass  # directory fsync is best-effort (not all filesystems)
    return len(payload)


def read_checkpoint(path: str) -> dict:
    """Load and verify a checkpoint; raises CheckpointError on any
    defect (the caller's contract: reject cleanly, start fresh)."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointError(f"unreadable checkpoint {path!r}: {e}")
    if len(blob) < len(MAGIC) + _HEADER.size:
        raise CheckpointError(f"truncated checkpoint header in {path!r}")
    if blob[:len(MAGIC)] != MAGIC:
        raise CheckpointError(f"bad checkpoint magic in {path!r}")
    version, length, crc = _HEADER.unpack_from(blob, len(MAGIC))
    if version != CKPT_VERSION:
        raise CheckpointError(
            f"checkpoint version {version} unsupported "
            f"(want {CKPT_VERSION})")
    payload = blob[len(MAGIC) + _HEADER.size:]
    if len(payload) != length:
        raise CheckpointError(
            f"checkpoint payload truncated: {len(payload)} != {length}")
    if zlib.crc32(payload) != crc:
        raise CheckpointError(f"checkpoint CRC mismatch in {path!r}")
    try:
        state = pickle.loads(zlib.decompress(payload))
    except Exception as e:
        raise CheckpointError(f"checkpoint payload undecodable: {e}")
    if not isinstance(state, dict):
        raise CheckpointError(
            f"checkpoint payload is {type(state).__name__}, not dict")
    return state
