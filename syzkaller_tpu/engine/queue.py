"""Prioritized fuzzing work queues (reference /root/reference/syz-fuzzer/
fuzzer.go:74-78,261-306: triageCandidate > candidate > triage > smash)."""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

from ..ipc import CallInfo
from ..prog.prog import Prog


@dataclass
class TriageItem:
    prog: Prog
    call_index: int
    signal: List[int]
    from_candidate: bool = False
    minimized: bool = False
    # provenance of the input that produced the new signal (phase +
    # mutation-operator indices) — the attribution ledger credits the
    # eventual corpus addition to it, not to the triage step
    origin: Optional[object] = None


@dataclass
class CandidateItem:
    prog: Prog
    minimized: bool = False


@dataclass
class SmashItem:
    prog: Prog
    call_index: int = -1


class WorkQueue:
    """Thread-safe priority-ordered queues. Pop order: candidate triage,
    candidates, triage, smash — starving smash work when triage backs up,
    exactly the reference's proc-loop priority ladder."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._triage_candidate: deque = deque()
        self._candidate: deque = deque()
        self._triage: deque = deque()
        self._smash: deque = deque()

    def push_triage(self, item: TriageItem) -> None:
        with self._lock:
            (self._triage_candidate if item.from_candidate
             else self._triage).append(item)

    def push_candidate(self, item: CandidateItem) -> None:
        with self._lock:
            self._candidate.append(item)

    def push_smash(self, item: SmashItem) -> None:
        with self._lock:
            self._smash.append(item)

    def pop(self):
        with self._lock:
            for q in (self._triage_candidate, self._candidate,
                      self._triage, self._smash):
                if q:
                    return q.popleft()
        return None

    def pop_triage_batch(self, n: int,
                         from_candidate: bool = False) -> List[TriageItem]:
        """Pop up to ``n`` more triage items from the SAME priority
        class as an already-popped head item (batched-bisection
        minimize, ISSUE 8): candidate-triage batches never mix with
        plain triage, so the reference's priority ladder ordering is
        preserved item-for-item."""
        out: List[TriageItem] = []
        with self._lock:
            q = self._triage_candidate if from_candidate else self._triage
            while q and len(out) < n:
                out.append(q.popleft())
        return out

    def depths(self):
        with self._lock:
            return {
                "triage_candidate": len(self._triage_candidate),
                "candidate": len(self._candidate),
                "triage": len(self._triage),
                "smash": len(self._smash),
            }

    def snapshot_items(self):
        """Consistent copy of all queued items in priority order, for the
        engine checkpoint (engine/checkpoint.py).  The items themselves
        are shared, not cloned — the caller serializes them immediately
        while no worker is draining."""
        with self._lock:
            return {
                "triage_candidate": list(self._triage_candidate),
                "candidate": list(self._candidate),
                "triage": list(self._triage),
                "smash": list(self._smash),
            }
