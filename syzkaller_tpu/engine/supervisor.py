"""Executor-env supervisor: the drain path's restart/backoff/quarantine
brain (the engine-side analogue of the reference manager's vmLoop, which
reschedules crashed VM instances instead of dying with them).

Per env the supervisor tracks consecutive failures and schedules
supervised restarts with jittered exponential backoff (an env that just
crashed is not immediately re-fed — thundering-herd restarts after a
correlated fault would re-crash the fleet in lockstep).  After
``quarantine_threshold`` consecutive failures the env is quarantined:
the batch fan-out re-shards its rows across the surviving envs, and the
quarantined env only sees periodic un-quarantine *probes* (one row per
``probe_interval``) — a probe success restores it to full service.

An optional per-call watchdog guards against the failure mode backoff
cannot see: a *wedged* env that neither fails nor returns.  Workers arm
a deadline around each exec; a single monitor thread scans the in-flight
table and, past the deadline, calls ``env.interrupt()`` (ipc kills the
executor process, unblocking the worker's pipe read into the ordinary
failure path) and counts ``env_watchdog_trips_total``.

All decisions are host-side and lock-cheap; the seeded jitter RNG makes
backoff schedules reproducible under the fault-injection harness.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..telemetry import get_registry


class _EnvState:
    __slots__ = ("failures", "not_before", "quarantined", "last_probe",
                 "last_backoff")

    def __init__(self):
        self.failures = 0
        self.not_before = 0.0
        self.quarantined = False
        self.last_probe = 0.0
        self.last_backoff = 0.0


class EnvSupervisor:
    """Supervision state machine over ``n_envs`` executor environments."""

    def __init__(self, n_envs: int, *, quarantine_threshold: int = 3,
                 base_backoff: float = 0.05, max_backoff: float = 5.0,
                 probe_interval: float = 1.0,
                 watchdog_seconds: float = 0.0, seed: int = 0,
                 registry=None, time_fn=time.monotonic,
                 on_event=None):
        self.n_envs = max(int(n_envs), 1)
        self.quarantine_threshold = max(int(quarantine_threshold), 1)
        self.base_backoff = float(base_backoff)
        self.max_backoff = float(max_backoff)
        self.probe_interval = float(probe_interval)
        self.watchdog_seconds = float(watchdog_seconds)
        self._time = time_fn
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._envs = [_EnvState() for _ in range(self.n_envs)]
        # state-transition hook (the engine's campaign-journal emit):
        # called OUTSIDE the supervisor lock with (event, **fields);
        # failures in the hook are swallowed — observability must never
        # take down the supervision it observes
        self._on_event = on_event

        reg = registry or get_registry()
        self._c_restarts = reg.counter(
            "env_restarts_total",
            help="supervised executor-env restarts scheduled after a "
                 "failure (backoff applies before the env is re-fed)")
        self._g_quarantined = reg.gauge(
            "env_quarantined",
            help="executor envs currently quarantined after repeated "
                 "consecutive failures")
        self._c_watchdog = reg.counter(
            "env_watchdog_trips_total",
            help="wedged executor calls interrupted by the per-call "
                 "watchdog deadline")
        self._c_probes = reg.counter(
            "env_unquarantine_probes_total",
            help="probe executions granted to quarantined envs")
        self._g_quarantined.set(0)
        # rows the drain gave up on after drain_max_attempts — the
        # supervision-local mirror of the engine's accounting, queryable
        # next to failures()/quarantined_count() (the operator surfaces
        # read the registry counter and the wire stat, not this)
        self._dropped_rows = 0

        # watchdog: in-flight exec deadlines, scanned by one monitor
        # thread (started lazily on the first guarded call)
        self._inflight: Dict[int, Tuple[float, object]] = {}
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    # ---- scheduling decisions (drain workers) ----

    def acquire(self, env_idx: int) -> bool:
        """May env ``env_idx`` take a row right now?  Quarantined envs
        are granted one probe per ``probe_interval``; envs inside their
        backoff window are refused."""
        now = self._time()
        with self._lock:
            st = self._envs[env_idx]
            if st.quarantined:
                if now - st.last_probe >= self.probe_interval:
                    st.last_probe = now
                    self._c_probes.inc()
                    return True
                return False
            return now >= st.not_before

    def usable_elsewhere(self, env_idx: int) -> bool:
        """True if any OTHER env is un-quarantined (this env's worker may
        leave its remaining rows to the survivors)."""
        with self._lock:
            return any(i != env_idx and not st.quarantined
                       for i, st in enumerate(self._envs))

    # ---- outcomes ----

    def record_failure(self, env_idx: int) -> None:
        """One exec failed on ``env_idx``: schedule a supervised restart
        with jittered exponential backoff; quarantine past the
        threshold."""
        with self._lock:
            st = self._envs[env_idx]
            st.failures += 1
            failures = st.failures
            self._c_restarts.inc()
            backoff = min(self.max_backoff,
                          self.base_backoff *
                          (2 ** min(st.failures - 1, 20)))
            backoff *= 0.5 + self._rng.random()  # jitter in [0.5, 1.5)
            st.last_backoff = backoff
            st.not_before = self._time() + backoff
            quarantined = False
            if not st.quarantined and \
                    st.failures >= self.quarantine_threshold:
                st.quarantined = quarantined = True
                self._update_quarantine_gauge_locked()
        self._emit("env_restart", env=env_idx, failures=failures,
                   backoff=round(backoff, 4))
        if quarantined:
            self._emit("env_quarantine", env=env_idx, failures=failures)

    def record_success(self, env_idx: int) -> None:
        """A clean exec on ``env_idx``: reset failures and, if this was
        an un-quarantine probe, restore the env to full service."""
        with self._lock:
            st = self._envs[env_idx]
            st.failures = 0
            st.not_before = 0.0
            unquarantined = False
            if st.quarantined:
                st.quarantined = False
                unquarantined = True
                self._update_quarantine_gauge_locked()
        if unquarantined:
            self._emit("env_unquarantine", env=env_idx)

    def _emit(self, ev: str, **fields) -> None:
        cb = self._on_event
        if cb is None:
            return
        try:
            cb(ev, **fields)
        except Exception:
            pass  # journaling must never take down supervision

    def record_dropped(self, n: int = 1) -> None:
        """The drain exhausted a row's retries across envs: the work is
        LOST, not just delayed.  This keeps the loss queryable from the
        supervision state machine (tests, tooling); the operator-facing
        surfaces are fed by the engine's drain_rows_dropped_total
        counter and ``drain_rows_dropped`` wire stat."""
        with self._lock:
            self._dropped_rows += int(n)

    def _update_quarantine_gauge_locked(self) -> None:
        self._g_quarantined.set(
            sum(1 for st in self._envs if st.quarantined))

    # ---- introspection (tests, dashboard) ----

    def dropped_rows(self) -> int:
        with self._lock:
            return self._dropped_rows

    def healthy_envs(self) -> List[int]:
        """Indices of envs currently fit for planned work (not
        quarantined) — the drain's prefix-group assignment prefers
        these so a whole group is never planned onto a sick env."""
        with self._lock:
            return [i for i, st in enumerate(self._envs)
                    if not st.quarantined]

    def is_quarantined(self, env_idx: int) -> bool:
        with self._lock:
            return self._envs[env_idx].quarantined

    def failures(self, env_idx: int) -> int:
        with self._lock:
            return self._envs[env_idx].failures

    def last_backoff(self, env_idx: int) -> float:
        with self._lock:
            return self._envs[env_idx].last_backoff

    def quarantined_count(self) -> int:
        with self._lock:
            return sum(1 for st in self._envs if st.quarantined)

    # ---- per-call watchdog ----

    def guard(self, env_idx: int, env):
        """Context manager arming the watchdog deadline around one exec;
        a no-op object when the watchdog is disabled (hot path stays
        allocation-light)."""
        if self.watchdog_seconds <= 0:
            return _NULL_GUARD
        return _Guard(self, env_idx, env)

    def _arm(self, env_idx: int, env) -> None:
        deadline = self._time() + self.watchdog_seconds
        with self._lock:
            self._inflight[env_idx] = (deadline, env)
            if self._monitor is None:
                self._monitor = threading.Thread(
                    target=self._monitor_loop, daemon=True,
                    name="syztpu-watchdog")
                self._monitor.start()

    def _disarm(self, env_idx: int) -> None:
        with self._lock:
            self._inflight.pop(env_idx, None)

    def _monitor_loop(self) -> None:
        poll = max(self.watchdog_seconds / 4.0, 0.005)
        while not self._stop.wait(poll):
            now = self._time()
            trips = []
            with self._lock:
                # interrupt UNDER the lock: a worker whose expired call
                # just returned blocks in _arm until the kill lands, so
                # the interrupt can only hit the expired exec (or an
                # idle env, which respawns silently) — never a healthy
                # next call that armed in between
                for k, (deadline, env) in list(self._inflight.items()):
                    if now <= deadline:
                        continue
                    del self._inflight[k]  # one trip per call
                    self._c_watchdog.inc()
                    trips.append(k)
                    interrupt = getattr(env, "interrupt", None)
                    if interrupt is not None:
                        try:
                            interrupt()
                        except Exception:
                            pass  # env already died: worker unblocks anyway
            for k in trips:
                self._emit("env_watchdog", env=k)

    def close(self) -> None:
        self._stop.set()
        m = self._monitor
        if m is not None:
            m.join(timeout=2.0)
            self._monitor = None


class _Guard:
    __slots__ = ("_sup", "_env_idx", "_env")

    def __init__(self, sup: EnvSupervisor, env_idx: int, env):
        self._sup = sup
        self._env_idx = env_idx
        self._env = env

    def __enter__(self):
        self._sup._arm(self._env_idx, self._env)
        return self

    def __exit__(self, *exc):
        self._sup._disarm(self._env_idx)


class _NullGuard:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


_NULL_GUARD = _NullGuard()
