"""syz-fuzzer binary equivalent: `python -m syzkaller_tpu.engine`.

Role parity with reference /root/reference/syz-fuzzer/fuzzer.go:98-136:
connect to the manager over RPC, build the call list (optionally probing
the live machine), run `procs` executor environments, fuzz until killed.
The manager's vmLoop starts this inside each VM instance.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-fuzzer")
    ap.add_argument("-manager", default="",
                    help="manager RPC address host:port")
    ap.add_argument("-name", default="fuzzer")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-frontend", "--frontend", default="syscall",
                    help="frontend to fuzz: syscall (kernel, default) or "
                    "hlo (XLA compiler, in-process differential executor)")
    ap.add_argument("-mock", action="store_true",
                    help="mock executor (hermetic)")
    ap.add_argument("-no-detect", action="store_true",
                    help="skip live supported-syscall detection")
    ap.add_argument("-device", action="store_true",
                    help="enable the TPU batched candidate pipeline")
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-iterations", type=int, default=0,
                    help="stop after N steps (0 = run forever)")
    ap.add_argument("-leak-check", action="store_true")
    ap.add_argument("-workdir", default="",
                    help="campaign working directory; enables periodic "
                    "atomic checkpoints to <workdir>/engine.ckpt")
    ap.add_argument("--resume", action="store_true",
                    help="restore engine state from <workdir>/engine.ckpt "
                    "(corrupt/missing checkpoints start fresh)")
    ap.add_argument("-checkpoint-interval", type=float, default=60.0,
                    help="seconds between periodic checkpoints")
    ap.add_argument("--telemetry-out", default="",
                    help="on exit, dump the telemetry document (metrics "
                    "snapshot + Chrome trace) to this JSON file")
    ap.add_argument("--no-spans", action="store_true",
                    help="disable span tracing (counters stay on)")
    args = ap.parse_args(argv)
    if args.resume and not args.workdir:
        ap.error("--resume requires -workdir (the checkpoint lives at "
                 "<workdir>/engine.ckpt)")

    from .. import frontends
    from ..telemetry import set_spans_enabled, telemetry_dump_to
    from .fuzzer import Fuzzer, FuzzerConfig

    # validate up front: an unknown frontend must die with the registry's
    # name list at argument-parse time (exit 2), not as an AttributeError
    # deep inside the first batch
    if args.frontend not in frontends.names():
        ap.error(f"unknown frontend {args.frontend!r} "
                 f"(available: {', '.join(frontends.names())})")

    if args.no_spans:
        set_spans_enabled(False)
    target = frontends.get(args.frontend).make_target(args.os, args.arch)
    manager = None
    if args.manager:
        from ..manager.rpc import RemoteManager

        manager = RemoteManager(args.manager, name=args.name)
    cfg = FuzzerConfig(
        procs=args.procs,
        mock=args.mock,
        use_device=args.device,
        sandbox=args.sandbox,
        frontend=args.frontend,
        # live syscall detection only makes sense against a kernel
        detect_supported=(not args.no_detect and not args.mock
                          and args.frontend == "syscall"),
        leak_check=args.leak_check,
        workdir=args.workdir,
        resume=args.resume,
        checkpoint_interval=args.checkpoint_interval,
    )
    f = Fuzzer(target, cfg, manager=manager)
    try:
        # poll the manager between bursts, like the reference's poll loop
        while True:
            f.loop(iterations=args.iterations or 100)
            f.poll_manager()
            if args.iterations:
                return 0
    except KeyboardInterrupt:
        return 0
    finally:
        # final checkpoint so a clean exit resumes exactly where it left
        if args.workdir:
            try:
                f.maybe_checkpoint(force=True)
            except Exception as e:
                print(f"final checkpoint failed: {e}", file=sys.stderr)
        # dump before close(): close detaches the weakref-bound gauges,
        # which would zero fuzzer_corpus_size etc. in the document
        if args.telemetry_out:
            err = telemetry_dump_to(args.telemetry_out)
            if err:
                print(f"telemetry dump failed: {err}", file=sys.stderr)
        f.close()


if __name__ == "__main__":
    sys.exit(main())
