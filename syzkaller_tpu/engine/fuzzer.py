"""The fuzzer brain: corpus, signal bookkeeping, triage/smash pipeline,
and the TPU candidate generator.

Role parity with reference /root/reference/syz-fuzzer/fuzzer.go:98-428
(proc loop, work-queue priorities, signal sets, triageInput:521-625,
smashInput:491-519), re-architected for the device: instead of one
mutation per loop iteration, candidates arrive in device-mutated *batches*
(ops/mutation.py) decoded through the tensor codec, and new-signal testing
against the accumulated max-signal runs as a packed-bitset gather
(ops/cover.py) — the BASELINE.json north-star path. Execution stays on the
CPU executor fleet through ipc.Env; a MockEnv makes the whole loop
hermetic.

Signal bookkeeping (fuzzer.go:65-68):
  corpus_signal — signal present in the corpus (exact host sets)
  max_signal   — everything ever seen (host set + device bitset mirror)
  new_signal   — delta not yet reported to the manager
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from ..ipc import CallInfo, Env, EnvConfig, ExecOpts, MockEnv
from ..prog.analysis import assign_sizes_call
from ..prog.encoding import serialize
from ..prog.generation import RandGen, generate
from ..prog.hints import CompMap, mutate_with_hints
from ..prog.mutation import minimize, mutate
from ..prog.prio import build_choice_table
from ..prog.prog import Prog
from ..utils.hash import hash_str
from .queue import CandidateItem, SmashItem, TriageItem, WorkQueue


@dataclass
class FuzzerConfig:
    procs: int = 1
    program_length: int = 16
    mock: bool = False                  # MockEnv instead of real executor
    use_device: bool = True             # TPU/JAX batched candidate path
    device_batch: int = 256
    generate_period: int = 100          # 1 generation per N mutations
    smash_mutations: int = 100          # reference fuzzer.go:498
    triage_reruns: int = 3              # reference fuzzer.go:540
    fault_injection: bool = False
    collect_comps: bool = False
    log_programs: bool = False          # emit `executing program` records
    sandbox: str = "none"
    device_period: int = 16             # consume a device batch every N steps
    mirror_bits: int = 1 << 20          # device max-signal bitset mirror
    env_config: Optional[EnvConfig] = None
    detect_supported: bool = False      # probe the live machine (pkg/host)
    leak_check: bool = False            # kmemleak scan every leak_period
    leak_period: int = 1000             # executions between scans


class ManagerConn:
    """Interface the engine talks to (reference rpctype Manager.*). The
    in-process default just accumulates; manager/rpc.py provides the real
    TCP client with identical methods."""

    def connect(self):
        return {"corpus": [], "prios": None, "max_signal": [],
                "candidates": [], "enabled": None}

    def new_input(self, prog_text: str, call_index: int,
                  signal: Sequence[int], cover: Sequence[int]) -> None:
        pass

    def poll(self, stats: Dict[str, int], need_candidates: bool,
             new_signal: Sequence[int] = ()):
        return {"new_inputs": [], "candidates": [], "max_signal": []}


class Fuzzer:
    def __init__(self, target, config: Optional[FuzzerConfig] = None,
                 manager: Optional[ManagerConn] = None, seed: int = 0):
        self.target = target
        self.cfg = config or FuzzerConfig()
        self.manager = manager or ManagerConn()
        self.rng = RandGen(target, seed=seed)
        self.queue = WorkQueue()
        self.stats: Dict[str, int] = {
            "exec_total": 0, "exec_gen": 0, "exec_fuzz": 0,
            "exec_candidate": 0, "exec_triage": 0, "exec_minimize": 0,
            "exec_smash": 0, "exec_hints": 0, "new_inputs": 0,
            "device_batches": 0, "device_candidates": 0,
        }
        self.corpus: List[Prog] = []
        self.corpus_hashes: Set[str] = set()
        self.corpus_signal: Set[int] = set()
        self.max_signal: Set[int] = set()
        self.new_signal: Set[int] = set()
        self._lock = threading.Lock()

        conn = self.manager.connect()
        self._enabled = conn.get("enabled")
        if self.cfg.detect_supported:
            # buildCallList (reference fuzzer.go:430-465): manager-enabled
            # calls intersected with what this machine supports, closed
            # under resource-ctor reachability
            from .. import host as _host

            self._enabled = sorted(_host.build_call_list(
                target, enabled=self._enabled))
        self.choice_table = build_choice_table(
            target, conn.get("prios"), self._enabled)
        self.max_signal.update(conn.get("max_signal", ()))
        for text in conn.get("corpus", ()):
            self._add_corpus_text(text)
        for text in conn.get("candidates", ()):
            self._push_candidate_text(text)

        self.envs: List = []
        for pid in range(self.cfg.procs):
            if self.cfg.mock:
                self.envs.append(MockEnv(target, pid=pid))
            else:
                ec = self.cfg.env_config or EnvConfig(sandbox=self.cfg.sandbox)
                self.envs.append(Env(target, pid=pid, config=ec))

        self._leak = None
        self.leak_reports = []
        self._next_leak_scan = self.cfg.leak_period
        if self.cfg.leak_check:
            from .kmemleak import Kmemleak

            self._leak = Kmemleak()

        self._device = None
        self._max_bits = None  # device bitset mirror of max_signal
        if self.cfg.use_device:
            try:
                self._device = _DevicePipeline(target, self.cfg)
                import numpy as _np

                # the mirror indexes by low hash bits: must be a power of
                # two or the (nbits-1) mask zeroes arbitrary positions
                nbits = 1 << (self.cfg.mirror_bits - 1).bit_length()
                self._max_bits = _np.zeros(nbits // 32, dtype=_np.uint32)
            except Exception:
                self._device = None  # no jax available: host-only mode

        self._iter = 0

    # ---- lifecycle ----

    def close(self) -> None:
        for e in self.envs:
            e.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- corpus ----

    def _add_corpus_text(self, text: str) -> None:
        from ..prog.encoding import deserialize

        try:
            p = deserialize(self.target, text)
        except Exception:
            return
        self._add_corpus(p, ())

    def _push_candidate_text(self, text: str) -> None:
        from ..prog.encoding import deserialize

        try:
            p = deserialize(self.target, text)
        except Exception:
            return
        self.queue.push_candidate(CandidateItem(p))

    def _add_corpus(self, p: Prog, signal: Sequence[int]) -> bool:
        h = hash_str(serialize(p).encode())
        with self._lock:
            if h in self.corpus_hashes:
                return False
            self.corpus_hashes.add(h)
            self.corpus.append(p)
            self.corpus_signal.update(signal)
        if self._device is not None:
            self._device.add_corpus(p)
        return True

    # ---- signal algebra (reference cover.SignalNew / SignalDiff) ----

    def _signal_new(self, sig: Sequence[int]) -> bool:
        return any(s not in self.max_signal for s in sig)

    def _signal_diff(self, sig: Sequence[int]) -> List[int]:
        return [s for s in sig if s not in self.max_signal]

    def _note_signal(self, sig: Sequence[int]) -> None:
        fresh = [s for s in sig if s not in self.max_signal]
        if fresh:
            self.max_signal.update(fresh)
            self.new_signal.update(fresh)

    def _fold_batch_signal(self, batch_sigs) -> None:
        """Fold one device batch's executed signal into the device bitset
        mirror with the fused one-pass kernel (ops/pallas_cover.py
        signal_stats; exact-set bookkeeping already happened per-program
        in execute()).  The per-batch new-bit count feeds the stats the
        manager graphs."""
        if self._max_bits is None or not batch_sigs:
            return
        import numpy as np

        nbits = self._max_bits.shape[0] * 32
        packed = np.zeros((len(batch_sigs), self._max_bits.shape[0]),
                          dtype=np.uint32)
        for i, sigs in enumerate(batch_sigs):
            if not sigs:
                continue
            h = np.asarray(sigs, dtype=np.uint64) & np.uint64(nbits - 1)
            np.bitwise_or.at(packed[i], (h >> np.uint64(5)).astype(np.int64),
                             np.uint32(1) << (h & np.uint64(31)).astype(np.uint32))
        from ..ops import pallas_cover

        counts, merged = pallas_cover.signal_stats(self._max_bits, packed)
        self._max_bits = np.asarray(merged, dtype=np.uint32)
        self.stats["device_new_bits"] = self.stats.get(
            "device_new_bits", 0) + int(np.asarray(counts).sum())

    # ---- execution ----

    def execute(self, p: Prog, stat: str = "exec_fuzz",
                opts: Optional[ExecOpts] = None, pid: int = 0,
                scan_new: bool = True) -> List[CallInfo]:
        """scan_new=False is the reference's executeRaw path
        (fuzzer.go:698): triage re-runs and minimize predicates must not
        re-enqueue triage work for the program's other calls."""
        opts = opts or ExecOpts()
        env = self.envs[pid % len(self.envs)]
        if self.cfg.log_programs:
            from ..utils.log import logf
            if opts.fault_call >= 0:
                logf(0, "executing program %d (fault-call:%d fault-nth:%d):\n%s",
                     pid, opts.fault_call, opts.fault_nth, serialize(p))
            else:
                logf(0, "executing program %d:\n%s", pid, serialize(p))
        _, infos, failed, hanged = env.exec(opts, p)
        self.stats["exec_total"] += 1
        self.stats[stat] = self.stats.get(stat, 0) + 1
        if failed or hanged or not scan_new:
            return infos
        # check per-call signal for novelty -> triage
        for info in infos:
            if info.index >= len(p.calls):
                continue
            diff = self._signal_diff(info.signal)
            if diff:
                self.queue.push_triage(TriageItem(
                    prog=p.clone(), call_index=info.index, signal=diff))
        return infos

    # ---- triage (reference triageInput fuzzer.go:521-625) ----

    def triage(self, item: TriageItem) -> None:
        opts = ExecOpts(collect_signal=True, collect_cover=True)
        inter: Optional[Set[int]] = None
        cover: Set[int] = set()
        for _ in range(self.cfg.triage_reruns):
            infos = self.execute(item.prog, "exec_triage", opts,
                                 scan_new=False)
            sig = self._call_signal(infos, item.call_index)
            if sig is None:
                continue
            cover.update(self._call_cover(infos, item.call_index) or ())
            inter = set(sig) if inter is None else (inter & set(sig))
            if not inter:
                return  # flaky signal: drop
        if not inter:
            return
        relevant = inter & set(item.signal) if item.signal else inter
        if item.signal and not relevant:
            return

        def pred(p: Prog, call_index: int) -> bool:
            infos = self.execute(p, "exec_minimize", opts, scan_new=False)
            sig = self._call_signal(infos, call_index)
            return sig is not None and relevant.issubset(set(sig))

        if not item.minimized:
            item.prog, item.call_index = minimize(
                item.prog, item.call_index, pred)

        sig_list = sorted(inter)
        self._note_signal(sig_list)
        if not self._add_corpus(item.prog, sig_list):
            return  # minimized to an already-known program
        self.stats["new_inputs"] += 1
        self.manager.new_input(serialize(item.prog), item.call_index,
                               sig_list, sorted(cover))
        self.queue.push_smash(SmashItem(item.prog, item.call_index))

    @staticmethod
    def _call_signal(infos: List[CallInfo], call_index: int
                     ) -> Optional[List[int]]:
        for info in infos:
            if info.index == call_index:
                # ipc pads calls the child never reached (executed=False,
                # errno=-1); treat those as "no result, retry" — not as
                # empty signal, which would make triage discard the input
                # on one flaky run (reference counts them as notexecuted)
                if not info.executed:
                    return None
                return info.signal
        return None

    @staticmethod
    def _call_cover(infos: List[CallInfo], call_index: int
                    ) -> Optional[List[int]]:
        for info in infos:
            if info.index == call_index:
                return info.cover
        return None

    # ---- smash (reference smashInput fuzzer.go:491-519) ----

    def smash(self, item: SmashItem) -> None:
        if self.cfg.collect_comps:
            self._hints_seed(item)
        if self.cfg.fault_injection and item.call_index >= 0:
            self._fail_call(item.prog, item.call_index)
        for i in range(self.cfg.smash_mutations):
            p = item.prog.clone()
            mutate(p, self.rng, self.cfg.program_length,
                   ct=self.choice_table, corpus=self.corpus)
            self.execute(p, "exec_smash")

    def _fail_call(self, p: Prog, call_index: int) -> None:
        for nth in range(100):  # 0-based; executor adds 1
            opts = ExecOpts(fault_call=call_index, fault_nth=nth)
            infos = self.execute(p, "exec_smash", opts)
            info = next((i for i in infos if i.index == call_index), None)
            if info is None or not info.fault_injected:
                break

    def _hints_seed(self, item: SmashItem) -> None:
        """reference executeHintSeed (fuzzer.go:627): exec with comps,
        then exec every hint mutant."""
        opts = ExecOpts(collect_signal=False, collect_comps=True)
        infos = self.execute(item.prog, "exec_hints", opts)
        comp_maps = []
        for i in range(len(item.prog.calls)):
            info = next((x for x in infos if x.index == i), None)
            comp_maps.append(CompMap.from_pairs(info.comps if info else ()))
        mutate_with_hints(item.prog, comp_maps,
                          lambda p: self.execute(p, "exec_hints"))

    # ---- the loop ----

    def step(self) -> None:
        """One scheduling decision (one iteration of the reference's
        proc loop, fuzzer.go:256-328)."""
        self._iter += 1
        # The TPU candidate factory runs on a fixed cadence regardless of
        # queue pressure — it is the primary fuzz source, double-buffered so
        # a batch is always cooking while the fleet executes the last one.
        if (self._device is not None and self.corpus
                and self._iter % self.cfg.device_period == 0):
            batch = self._device.candidates(self.corpus)
            if batch:
                self.stats["device_batches"] += 1
                self.stats["device_candidates"] += len(batch)
                batch_sigs = []
                for p in batch:
                    infos = self.execute(p, "exec_fuzz")
                    batch_sigs.append(sorted(
                        {s for info in infos or () for s in info.signal}))
                self._fold_batch_signal(batch_sigs)
                return
        item = self.queue.pop()
        if isinstance(item, TriageItem):
            self.triage(item)
            return
        if isinstance(item, CandidateItem):
            self.execute(item.prog, "exec_candidate")
            return
        if isinstance(item, SmashItem):
            self.smash(item)
            return
        if not self.corpus or self._iter % self.cfg.generate_period == 0:
            p = generate(self.target, self.rng, self.cfg.program_length,
                         self.choice_table)
            self.execute(p, "exec_gen")
        else:
            p = self.corpus[self.rng.intn(len(self.corpus))].clone()
            mutate(p, self.rng, self.cfg.program_length,
                   ct=self.choice_table, corpus=self.corpus)
            self.execute(p, "exec_fuzz")

    def loop(self, iterations: int = 0, duration: float = 0.0) -> None:
        t0 = time.time()
        i = 0
        while True:
            if iterations and i >= iterations:
                break
            if duration and time.time() - t0 >= duration:
                break
            self.step()
            i += 1
            if self._leak is not None and \
                    self.stats["exec_total"] >= self._next_leak_scan:
                self._next_leak_scan = self.stats["exec_total"] + \
                    self.cfg.leak_period
                leaks = self._leak.scan()
                if leaks:
                    self.leak_reports.extend(leaks)
                    del self.leak_reports[:-100]
                    self.stats["leaks"] = self.stats.get("leaks", 0) + \
                        len(leaks)

    def poll_manager(self) -> None:
        """Exchange stats/new-signal with the manager (fuzzer.go:334-427)."""
        stats = dict(self.stats)
        r = self.manager.poll(stats, need_candidates=not self.corpus,
                              new_signal=sorted(self.new_signal))
        for text in r.get("new_inputs", ()):
            self._add_corpus_text(text)
        for text in r.get("candidates", ()):
            self._push_candidate_text(text)
        self.max_signal.update(r.get("max_signal", ()))
        self.new_signal.clear()


class _DevicePipeline:
    """Device-side candidate factory: keeps an encoded mirror of the corpus
    and emits batches of device-mutated candidates, double-buffered so the
    TPU mutates batch N+1 while the executor fleet runs batch N (SURVEY §7
    hard part #3)."""

    def __init__(self, target, cfg: FuzzerConfig):
        import jax

        from ..descriptions.tables import get_tables
        from ..ops.dtables import build_device_tables
        from ..ops import mutation as dmut
        from ..prog.tensor import ProgBatch, TensorFormat, encode_prog

        self._jax = jax
        self._dmut = dmut
        self.tables = get_tables(target)
        self.fmt = TensorFormat.for_tables(
            self.tables, max_calls=cfg.program_length)
        self.dt = build_device_tables(self.tables, self.fmt)
        self.B = cfg.device_batch
        self._ProgBatch = ProgBatch
        self._encode_prog = encode_prog
        self._key = jax.random.PRNGKey(1)
        self._pick = __import__("numpy").random.default_rng(1)
        self._pending = None  # in-flight device computation (double buffer)
        self.target = target
        self._corpus_encoded: List = []

    def add_corpus(self, p: Prog) -> None:
        batch = self._ProgBatch.empty(self.fmt, 1)
        try:
            self._encode_prog(self.tables, self.fmt, p, batch, 0)
        except Exception:
            return  # long-tail arg the tensor format can't carry yet
        self._corpus_encoded.append(
            (batch.call_id[0], batch.slot_val[0], batch.data[0]))

    def _launch(self):
        import numpy as np

        jax = self._jax
        n = len(self._corpus_encoded)
        if n == 0:
            return None
        self._key, kmut = jax.random.split(self._key)
        idx = self._pick.integers(0, n, size=self.B)
        cid = np.stack([self._corpus_encoded[i][0] for i in idx])
        sval = np.stack([self._corpus_encoded[i][1] for i in idx])
        data = np.stack([self._corpus_encoded[i][2] for i in idx])
        return self._dmut.mutate_batch(kmut, self.dt, cid, sval, data)

    def candidates(self, corpus: List[Prog]) -> List[Prog]:
        """Return the previously launched batch (decoded) and launch the
        next one."""
        from ..prog.tensor import decode_prog

        import numpy as np

        done = self._pending
        self._pending = self._launch()
        if done is None:
            return []
        cid, sval, data = (np.asarray(x) for x in done)
        batch = self._ProgBatch(call_id=cid, slot_val=sval, data=data)
        out: List[Prog] = []
        for i in range(cid.shape[0]):
            try:
                p = decode_prog(self.tables, self.fmt, batch, i)
            except Exception:
                continue
            for c in p.calls:
                self.target.sanitize_call(c)
                assign_sizes_call(self.target, c)
            out.append(p)
        return out
