"""The fuzzer brain: corpus, signal bookkeeping, triage/smash pipeline,
and the TPU candidate generator.

Role parity with reference /root/reference/syz-fuzzer/fuzzer.go:98-428
(proc loop, work-queue priorities, signal sets, triageInput:521-625,
smashInput:491-519), re-architected for the device: instead of one
mutation per loop iteration, candidates arrive in device-mutated *batches*
(ops/mutation.py) decoded through the tensor codec, and new-signal testing
against the accumulated max-signal runs as a packed-bitset gather
(ops/cover.py) — the BASELINE.json north-star path. Execution stays on the
CPU executor fleet through ipc.Env; a MockEnv makes the whole loop
hermetic.

Signal bookkeeping (fuzzer.go:65-68):
  corpus_signal — signal present in the corpus (exact host sets)
  max_signal   — everything ever seen (host set + device bitset mirror)
  new_signal   — delta not yet reported to the manager
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set

from ..ipc import CallInfo, EnvConfig, ExecOpts
from ..prog.analysis import assign_sizes_call
from ..telemetry import (
    Provenance,
    count_error,
    get_ledger,
    get_registry,
    ops_from_mask,
    record_span,
    span,
    timed,
)
from ..telemetry import attribution as _attr
from ..telemetry import journal as _journal
from ..testing import faults as _faults
from ..prog.encoding import serialize
from ..prog.generation import RandGen, generate
from ..prog.hints import CompMap, mutate_with_hints
from ..prog.mutation import minimize, mutate
from ..prog.prio import build_choice_table
from ..prog.prog import Prog
from ..utils.hash import hash_str
from . import checkpoint as _ckpt
from .queue import CandidateItem, SmashItem, TriageItem, WorkQueue
from .supervisor import EnvSupervisor

# exec-stat -> attribution phase (the stat strings are the RPC wire
# vocabulary; the ledger speaks the ISSUE 2 phase vocabulary)
_STAT_PHASE = {
    "exec_gen": _attr.PHASE_GENERATE,
    "exec_fuzz": _attr.PHASE_MUTATE,
    "exec_smash": _attr.PHASE_SMASH,
    "exec_hints": _attr.PHASE_HINTS,
    "exec_candidate": _attr.PHASE_CANDIDATE,
    "exec_triage": _attr.PHASE_TRIAGE,
    "exec_minimize": _attr.PHASE_TRIAGE,
}

# arena yield credit for a triaged corpus addition (on top of 1 point
# per fresh max-signal PC) — an input good enough to join the corpus is
# a strictly stronger signal than raw new PCs
_CORPUS_ADD_CREDIT = 8.0


@dataclass
class FuzzerConfig:
    procs: int = 1
    program_length: int = 16
    mock: bool = False                  # MockEnv instead of real executor
    use_device: bool = True             # TPU/JAX batched candidate path
    device_batch: int = 256
    generate_period: int = 100          # 1 generation per N mutations
    smash_mutations: int = 100          # reference fuzzer.go:498
    triage_reruns: int = 3              # reference fuzzer.go:540
    fault_injection: bool = False
    collect_comps: bool = False
    log_programs: bool = False          # emit `executing program` records
    sandbox: str = "none"
    device_period: int = 16             # consume a device batch every N steps
    # depth of the device launch ring: how many sharded steps may be
    # in flight (launched, not yet consumed) at once.  1 restores the
    # old lockstep double buffer; >=2 overlaps device compute + D2H
    # transfer with the host executor drain (each launch is an async
    # enqueue, each output starts copy_to_host_async immediately, and
    # the drain consumes whichever batch's transfer completes first)
    pipeline_depth: int = 2
    # device-resident corpus arena rows (ops/arena.py): encoded programs
    # stay on the chips; eviction beyond this prefers the lowest-yield
    # row (FIFO among ties — see ops/arena.CorpusArena)
    arena_capacity: int = 1024
    # ---- device-side candidate admission (ops/admission.py) ----
    # recent-hash Bloom filter bits (rounded up to a power of two) and
    # probe count; the filter resets once occupancy crosses the decay
    # threshold (a brief dedup blind spot bounds the false-positive
    # rate, which grows like occupancy**probes)
    admission_bloom_bits: int = 1 << 20
    admission_probes: int = 4
    admission_bloom_decay: float = 0.5
    # device signal bitsets (sharded proxy set + host max-signal mirror):
    # sized like ops/cover.DEFAULT_BITS — a small mirror saturates with
    # collisions on a real corpus
    mirror_bits: int = 1 << 26
    env_config: Optional[EnvConfig] = None
    detect_supported: bool = False      # probe the live machine (pkg/host)
    leak_check: bool = False            # kmemleak scan every leak_period
    leak_period: int = 1000             # executions between scans
    # ---- campaign supervision ----
    workdir: str = ""                   # engine.ckpt lives here ("" = off)
    resume: bool = False                # restore workdir/engine.ckpt at init
    checkpoint_interval: float = 60.0   # seconds between checkpoints
    env_quarantine_threshold: int = 3   # consecutive failures -> quarantine
    env_base_backoff: float = 0.05      # first supervised-restart delay (s)
    env_max_backoff: float = 5.0        # backoff ceiling (s)
    env_probe_interval: float = 1.0     # quarantined-env probe cadence (s)
    env_watchdog_seconds: float = 0.0   # per-exec watchdog deadline (0=off)
    drain_max_attempts: int = 3         # per-row attempts across envs
    # ---- prefix-memoized batch execution (ops/prefix.py + ipc) ----
    # build a prefix tree over each staged batch and schedule one prefix
    # job per tree node + per-program suffix jobs, env-affine by group
    prefix_schedule: bool = True
    prefix_min_group: int = 2           # min users to pay for a node
    prefix_min_calls: int = 1           # min shared ACTIVE calls memoized
    prefix_cache_entries: int = 1024    # per-env continuation LRU bound
    # arena yield age-decay (geometric), applied on the existing
    # occupancy-triggered admission-Bloom reset so early-campaign
    # jackpot rows stop pinning the weighted sampler forever
    arena_yield_decay: float = 0.5
    # ---- batched-bisection triage minimize (ISSUE 8) ----
    # drain every queued triage item of one priority class together and
    # run their rerun + minimize ladders as fleet-wide probe ROUNDS
    # (one batch of probe executions per round) instead of one serial
    # exec round-trip per probe per item
    minimize_bisect: bool = True
    minimize_batch: int = 8             # max triage items per batch
    # ---- durable campaign journal (telemetry/journal.py) ----
    # enabled whenever a workdir is configured: every state transition
    # (checkpoints, env supervision, degradation, admission resets,
    # corpus adds) lands in <workdir>/journal.jsonl, bounded by
    # journal_max_bytes * journal_segments and replayable offline
    journal: bool = True
    journal_max_bytes: int = 4 << 20
    journal_segments: int = 4
    # ---- frontend selection (frontends/__init__.py registry) ----
    # which (target, executor) pair the campaign fuzzes: "syscall" is
    # the kernel-fuzzing default (parity-pinned), "hlo" the in-process
    # XLA compiler-fuzzing frontend.  Everything above the env boundary
    # is frontend-agnostic.
    frontend: str = "syscall"


class ManagerConn:
    """Interface the engine talks to (reference rpctype Manager.*). The
    in-process default just accumulates; manager/rpc.py provides the real
    TCP client with identical methods."""

    def connect(self):
        return {"corpus": [], "prios": None, "max_signal": [],
                "candidates": [], "enabled": None}

    def new_input(self, prog_text: str, call_index: int,
                  signal: Sequence[int], cover: Sequence[int]) -> None:
        pass

    def poll(self, stats: Dict[str, int], need_candidates: bool,
             new_signal: Sequence[int] = (), ledger=None):
        return {"new_inputs": [], "candidates": [], "max_signal": []}


class Fuzzer:
    def __init__(self, target, config: Optional[FuzzerConfig] = None,
                 manager: Optional[ManagerConn] = None, seed: int = 0):
        self.target = target
        self.cfg = config or FuzzerConfig()
        self.manager = manager or ManagerConn()
        self.rng = RandGen(target, seed=seed)
        self.queue = WorkQueue()
        self.stats: Dict[str, int] = {
            "exec_total": 0, "exec_gen": 0, "exec_fuzz": 0,
            "exec_candidate": 0, "exec_triage": 0, "exec_minimize": 0,
            "exec_smash": 0, "exec_hints": 0, "new_inputs": 0,
            "device_batches": 0, "device_candidates": 0,
        }
        self.corpus: List[Prog] = []
        self.corpus_hashes: Set[str] = set()
        self.corpus_signal: Set[int] = set()
        self.max_signal: Set[int] = set()
        self.new_signal: Set[int] = set()
        self._lock = threading.Lock()
        # guards the wire-stat dict: the parallel device-batch drain bumps
        # exec counters from worker threads (_record_exec)
        self._stats_lock = threading.Lock()
        self._drain_pool = None  # lazy ThreadPoolExecutor over self.envs

        # telemetry: self.stats stays the RPC wire shape; the registry
        # carries the same counters plus latencies for /metrics and BENCH.
        # Metric objects are bound once here — the hot path must pay one
        # locked add, not a registry lookup (ISSUE 1 overhead bound).
        reg = get_registry()
        self.metrics = reg
        # phase/operator yield accounting (bound once — hot path)
        self._ledger = get_ledger()
        self._m_exec_total = reg.counter(
            "exec_total", help="programs executed")
        self._m_new_inputs = reg.counter(
            "new_inputs_total", help="inputs triaged into the corpus")
        self._m_new_signal = reg.counter(
            "new_signal_total", help="new signal PCs accepted")
        self._m_device_batches = reg.counter(
            "device_batches_total", help="device candidate batches consumed")
        self._m_device_candidates = reg.counter(
            "device_candidates_total", help="device-mutated candidates run")
        self._h_device_batch = reg.histogram(
            "device_batch_latency_seconds",
            help="wall time to execute one device candidate batch")
        self._h_triage = reg.histogram(
            "triage_latency_seconds", help="wall time of one triage job")
        self._h_smash = reg.histogram(
            "smash_latency_seconds", help="wall time of one smash job")
        self._h_generate = reg.histogram(
            "generate_latency_seconds",
            help="wall time of one host generation")
        self._h_signal_fold = reg.histogram(
            "signal_fold_seconds",
            help="host fold of a device batch's signal into the mirror")
        self._g_drain_occupancy = reg.gauge(
            "device_drain_env_occupancy",
            help="fraction of executor envs that ran rows in the last "
                 "device-batch drain")
        # campaign supervision: checkpoint + RPC + drain-retry accounting
        # (rpc_errors_total itself is owned by manager/rpc.RemoteManager —
        # one counter per transport attempt; engine-level sync failures
        # land in errors_rpc_poll_total via count_error, not here, so one
        # logical failure is never counted twice)
        self._pending_new_inputs: deque = deque()
        self._h_ckpt_write = reg.histogram(
            "checkpoint_write_seconds",
            help="wall time of one atomic engine checkpoint write")
        self._m_ckpt_writes = reg.counter(
            "checkpoint_writes_total", help="engine checkpoints written")
        self._m_ckpt_restores = reg.counter(
            "checkpoint_restores_total",
            help="engine checkpoints restored on resume")
        self._m_ckpt_rejected = reg.counter(
            "checkpoint_rejected_total",
            help="checkpoints rejected at resume (corrupt, truncated, or "
                 "incompatible) — the engine starts fresh instead")
        self._m_rows_dropped = reg.counter(
            "drain_rows_dropped_total",
            help="device-batch rows dropped after exhausting drain "
                 "retries across envs")
        # prefix-memoized batch execution: hit = a grouped row whose
        # memoized prefix was reused (continuation splice on a
        # fork-capable env, or triage-signal reuse on the fallback
        # path); miss = a grouped row that had to pay the full prefix
        self._m_prefix_hits = reg.counter(
            "prefix_cache_hits_total",
            help="grouped drain rows that reused a memoized prefix "
                 "(continuation splice or fallback triage-signal reuse)")
        self._m_prefix_misses = reg.counter(
            "prefix_cache_misses_total",
            help="grouped drain rows executed without a usable "
                 "memoized prefix (cold cache, re-planned group, or "
                 "first member of a group on a fallback env)")
        # cache-warmer executions are counted HERE, not in exec_total:
        # a prefix job completes no program, and folding it into the
        # exec counters would bias every off-vs-on bench comparison
        self._m_prefix_jobs = reg.counter(
            "prefix_jobs_total",
            help="prefix cache-warmer executions scheduled by the "
                 "drain (not counted in exec_total — they complete no "
                 "program)")
        # batched-bisection triage minimize (ISSUE 8): rounds are the
        # serial-round-trip axis the bench compares against the old
        # one-exec-per-probe path; batch execs are the probes they carry
        self._m_bisect_rounds = reg.counter(
            "minimize_bisect_rounds_total",
            help="batched-bisection triage rounds executed (one "
                 "fleet-wide probe batch per round — the serial exec "
                 "round-trip axis batching collapses)")
        self._m_bisect_execs = reg.counter(
            "minimize_batch_execs_total",
            help="probe executions carried by batched-bisection triage "
                 "rounds (also counted in exec_triage/exec_minimize)")
        # engine-side memo of which prefix hashes have had their signal
        # scanned for novelty once (bounded LRU-set; guards the triage
        # scan skip for both the continuation and the fallback path)
        self._prefix_scanned: "OrderedDict[int, bool]" = OrderedDict()
        self._prefix_scanned_lock = threading.Lock()
        self._last_ckpt_time = 0.0
        # fuzzer_-prefixed: the manager owns the bare corpus_size gauge,
        # and in-process deployments share one registry.  Weakref-bound
        # and detached in close(): the registry outlives fuzzer
        # instances and must not pin a dead one's corpus alive
        ref = weakref.ref(self)
        self._gauge_fns = [
            (reg.gauge("fuzzer_corpus_size",
                       help="programs in this fuzzer's corpus"),
             lambda: len(s.corpus) if (s := ref()) is not None else 0),
            (reg.gauge("fuzzer_max_signal_size",
                       help="accumulated max-signal PCs"),
             lambda: len(s.max_signal) if (s := ref()) is not None else 0),
            (reg.gauge("checkpoint_age_seconds",
                       help="seconds since the last engine checkpoint "
                            "was written (-1 before the first write)"),
             lambda: ((time.time() - s._last_ckpt_time)
                      if (s := ref()) is not None and s._last_ckpt_time
                      else -1.0)),
        ]
        for g, fn in self._gauge_fns:
            g.set_fn(fn)

        # device pipeline fields exist BEFORE the manager connect below:
        # a manager with a corpus hands it over at connect time, and
        # _add_corpus consults self._device for every import (the
        # pipeline itself is built after the env fleet)
        self._device = None
        self._max_bits = None  # device bitset mirror of max_signal
        # triage novelty SCREEN (ISSUE 8): a packed-bitset superset
        # image of max_signal — every member's bit is set, so a CLEAR
        # bit proves a signal is new and the drain's novelty scans can
        # run as one fused merge_and_new pass instead of a per-signal
        # python set walk.  Maintained at every max_signal growth site
        # (_screen_note); allocated before connect (which imports the
        # manager's max_signal).  Host-only engines keep the exact walk.
        self._tri_bits = None
        if self.cfg.use_device:
            import numpy as _np

            nbits = 1 << (self.cfg.mirror_bits - 1).bit_length()
            self._tri_bits = _np.zeros(nbits // 32, dtype=_np.uint32)

        # ---- durable identity + campaign journal (before anything
        # that emits: manager connect imports seed corpus entries) ----
        # engine_id is minted once per workdir (ephemeral without one)
        # and stamped into wire stats, checkpoints, and every journal
        # record, so a --resume run continues the SAME trajectory and
        # fleet tooling can dedup/attribute by engine
        if self.cfg.workdir:
            os.makedirs(self.cfg.workdir, exist_ok=True)
        self.engine_id = _journal.mint_engine_id(self.cfg.workdir)
        self._journal: Optional[_journal.CampaignJournal] = None
        if self.cfg.workdir and self.cfg.journal:
            self._journal = _journal.CampaignJournal(
                os.path.join(self.cfg.workdir, _journal.JOURNAL_NAME),
                engine_id=self.engine_id,
                max_bytes=self.cfg.journal_max_bytes,
                segments=self.cfg.journal_segments)
            self._jemit("campaign_start", resume=bool(self.cfg.resume),
                        procs=self.cfg.procs, mock=self.cfg.mock,
                        device=self.cfg.use_device)

        conn = self.manager.connect()
        self._enabled = conn.get("enabled")
        if self.cfg.detect_supported:
            # buildCallList (reference fuzzer.go:430-465): manager-enabled
            # calls intersected with what this machine supports, closed
            # under resource-ctor reachability
            from .. import host as _host

            self._enabled = sorted(_host.build_call_list(
                target, enabled=self._enabled))
        self.choice_table = build_choice_table(
            target, conn.get("prios"), self._enabled)
        self.max_signal.update(conn.get("max_signal", ()))
        self._screen_note(conn.get("max_signal", ()))
        for text in conn.get("corpus", ()):
            self._add_corpus_text(text)
        for text in conn.get("candidates", ()):
            self._push_candidate_text(text)

        # env construction goes through the frontend registry: the
        # default "syscall" frontend reproduces the historical MockEnv /
        # Env loop exactly (parity-pinned by tests/test_frontends.py),
        # "hlo" swaps in the in-process differential executor — same
        # drain/supervision/prefix machinery either way.
        from .. import frontends as _frontends

        self.frontend = _frontends.get(self.cfg.frontend)
        self.envs: List = []
        for pid in range(self.cfg.procs):
            self.envs.append(self.frontend.make_env(target, pid, self.cfg))
        # drain-path supervision: backoff/quarantine/watchdog over the fleet
        self.supervisor = EnvSupervisor(
            len(self.envs),
            quarantine_threshold=self.cfg.env_quarantine_threshold,
            base_backoff=self.cfg.env_base_backoff,
            max_backoff=self.cfg.env_max_backoff,
            probe_interval=self.cfg.env_probe_interval,
            watchdog_seconds=self.cfg.env_watchdog_seconds,
            seed=seed, on_event=self._jemit)

        self._leak = None
        self.leak_reports = []
        self._next_leak_scan = self.cfg.leak_period
        if self.cfg.leak_check:
            from .kmemleak import Kmemleak

            self._leak = Kmemleak()

        if self.cfg.use_device:
            try:
                self._device = _DevicePipeline(target, self.cfg,
                                               journal=self._jemit)
                import numpy as _np

                # the mirror indexes by low hash bits: must be a power of
                # two or the (nbits-1) mask zeroes arbitrary positions
                nbits = 1 << (self.cfg.mirror_bits - 1).bit_length()
                self._max_bits = _np.zeros(nbits // 32, dtype=_np.uint32)
            except Exception as e:
                count_error("device_init", e)
                self._device = None  # no jax available: host-only mode
            if self._device is not None:
                # corpus imported at connect time predates the pipeline:
                # seed the arena so the device path starts on the full
                # corpus instead of waiting for fresh triage adds
                with self._lock:
                    seeded = list(self.corpus)
                for p in seeded:
                    self._device.add_corpus(p)

        self._iter = 0

        # checkpoint/resume: workdir/engine.ckpt is this engine's
        # corpus.db analogue — see engine/checkpoint.py
        self.checkpoint_path = (
            os.path.join(self.cfg.workdir, "engine.ckpt")
            if self.cfg.workdir else "")
        self._next_ckpt = time.monotonic() + max(
            self.cfg.checkpoint_interval, 0.0)
        if self.cfg.resume and self.checkpoint_path and \
                os.path.exists(self.checkpoint_path):
            self.restore()

        # install as the process-global hook LAST — far call sites (RPC
        # reconnects, manager crash saves) emit through it, and a failed
        # __init__ (manager down, bad checkpoint config) must not leave
        # the hook pointing at an orphaned journal, blocking the next
        # engine's install; the first live journal owns the hook and
        # close() releases it
        if self._journal is not None and _journal.get_journal() is None:
            _journal.install(self._journal)

    # ---- lifecycle ----

    def _jemit(self, ev: str, **fields) -> None:
        """Emit one campaign-journal event (no-op without a workdir) —
        the single funnel the supervisor/device/checkpoint hooks share."""
        if self._journal is not None:
            self._journal.emit(ev, **fields)

    def close(self) -> None:
        if self._drain_pool is not None:
            self._drain_pool.shutdown(wait=True)
            self._drain_pool = None
        for e in self.envs:
            e.close()
        self.supervisor.close()
        for g, fn in getattr(self, "_gauge_fns", ()):
            g.clear_fn(fn)
        if self._device is not None:
            self._device.close()
        # flush-on-exit: the terminal record + fsync make the clean-exit
        # journal durable end-to-end (a SIGKILL'd engine instead loses
        # at most the last in-flight record — the chaos-pinned bound)
        if self._journal is not None:
            with self._stats_lock:
                execs = self.stats.get("exec_total", 0)
                ni = self.stats.get("new_inputs", 0)
            self._journal.emit("campaign_end", execs=execs,
                               new_inputs=ni,
                               signal=len(self.max_signal))
            if _journal.get_journal() is self._journal:
                _journal.install(None)
            self._journal.close()
            self._journal = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- corpus ----

    def _add_corpus_text(self, text: str) -> None:
        from ..prog.encoding import deserialize

        try:
            p = deserialize(self.target, text)
        except Exception as e:
            # a corrupt corpus entry from the manager sync must not kill
            # the loop, but it must not vanish either
            count_error("corpus_deserialize", e)
            return
        if self._add_corpus(p, ()):
            # connect-time corpus import: credited to the seed phase (no
            # exec paid, no new_inputs bump — triaged work never lands
            # here), so seed volume is auditable next to earned yield
            self._ledger.record_corpus_add(_attr.PHASE_SEED)
            self._jemit("corpus_add", phase=_attr.PHASE_SEED,
                        h=hash_str(text.encode())[:16])

    def _push_candidate_text(self, text: str) -> None:
        from ..prog.encoding import deserialize

        try:
            p = deserialize(self.target, text)
        except Exception as e:
            count_error("candidate_deserialize", e)
            return
        self.queue.push_candidate(CandidateItem(p))

    def _add_corpus(self, p: Prog, signal: Sequence[int]) -> bool:
        h = hash_str(serialize(p).encode())
        with self._lock:
            if h in self.corpus_hashes:
                return False
            self.corpus_hashes.add(h)
            self.corpus.append(p)
            self.corpus_signal.update(signal)
        if self._device is not None:
            self._device.add_corpus(p)
        return True

    # ---- signal algebra (reference cover.SignalNew / SignalDiff) ----

    def _signal_new(self, sig: Sequence[int]) -> bool:
        return any(s not in self.max_signal for s in sig)

    def _signal_diff(self, sig: Sequence[int]) -> List[int]:
        return [s for s in sig if s not in self.max_signal]

    def _note_signal(self, sig: Sequence[int]) -> int:
        fresh = [s for s in sig if s not in self.max_signal]
        if fresh:
            self.max_signal.update(fresh)
            self.new_signal.update(fresh)
            self._m_new_signal.inc(len(fresh))
            self._screen_note(fresh)
        return len(fresh)

    def _screen_note(self, sigs) -> None:
        """Mirror a max-signal addition into the triage novelty screen.
        The screen's soundness (bit clear => the signal is definitely
        NOT in max_signal) requires every member's bit to be set, so
        every growth site of max_signal funnels here."""
        if self._tri_bits is None or not sigs:
            return
        from ..ops import cover as _cover

        _cover.bitset_add_host(self._tri_bits, sigs)

    @staticmethod
    def _pack_signal_rows(rows):
        """SENT-pad a ragged list of signal lists into the [N, S] u32
        array the fused merge_and_new entries consume (values wrap to
        u32 exactly like the bitset index mapping does)."""
        import numpy as np

        s = max((len(r) for r in rows), default=0)
        arr = np.full((len(rows), s), 0xFFFFFFFF, dtype=np.uint32)
        for k, sig in enumerate(rows):
            if sig:
                a = np.asarray(sig, dtype=np.uint64) & \
                    np.uint64(0xFFFFFFFF)
                arr[k, :a.size] = a.astype(np.uint32)
        return arr

    def _fold_batch_signal(self, batch_sigs) -> None:
        """Fold one device batch's executed signal into the max-signal
        bitset mirror via the fused merge + new-signal entry
        (ops/cover.merge_and_new_host, ISSUE 8): one pass computes the
        per-row popcount-delta counts AND updates the accumulator in
        place — no per-row gather/scatter split, no dense [B, W] pack
        (at DEFAULT_BITS-scale that would be gigabytes).  The summed
        count feeds the stats the manager graphs; exact-set bookkeeping
        already happened per-program in execute()."""
        if self._max_bits is None:
            return
        from ..ops import cover as _cover

        rows = [s for s in batch_sigs if s]
        if not rows:
            return
        t0 = time.perf_counter()
        counts, _mask, _ = _cover.merge_and_new_host(
            self._max_bits, self._pack_signal_rows(rows), update=True)
        self.stats["device_new_bits"] = self.stats.get(
            "device_new_bits", 0) + int(counts.sum())
        self._h_signal_fold.observe(time.perf_counter() - t0)

    # ---- execution ----

    def _record_exec(self, stat: str, origin: Provenance) -> None:
        """The one locked update for execution accounting: every exec path
        — the serial loop and the parallel drain workers alike — lands
        here, so the wire-stat dict stays consistent under the fan-out.
        The exec_* counters are initialized in __init__, hence the plain
        ``+= 1`` (an unknown stat string is a bug worth a KeyError)."""
        with self._stats_lock:
            self.stats["exec_total"] += 1
            self.stats[stat] += 1
        self._m_exec_total.inc()
        self._ledger.record_exec(origin.phase, origin.ops)

    def execute(self, p: Prog, stat: str = "exec_fuzz",
                opts: Optional[ExecOpts] = None, pid: int = 0,
                scan_new: bool = True,
                origin: Optional[Provenance] = None) -> List[CallInfo]:
        """scan_new=False is the reference's executeRaw path
        (fuzzer.go:698): triage re-runs and minimize predicates must not
        re-enqueue triage work for the program's other calls.

        ``origin`` is the program's provenance (phase + mutation operator
        indices); it rides any TriageItems this execution enqueues so the
        attribution ledger credits corpus yield to the producing phase."""
        opts = opts or ExecOpts()
        env = self.envs[pid % len(self.envs)]
        if self.cfg.log_programs:
            from ..utils.log import logf
            if opts.fault_call >= 0:
                logf(0, "executing program %d (fault-call:%d fault-nth:%d):\n%s",
                     pid, opts.fault_call, opts.fault_nth, serialize(p))
            else:
                logf(0, "executing program %d:\n%s", pid, serialize(p))
        _, infos, failed, hanged = env.exec(opts, p)
        if origin is None:
            origin = Provenance(_STAT_PHASE.get(stat, stat))
        self._record_exec(stat, origin)
        if failed or hanged or not scan_new:
            return infos
        # check per-call signal for novelty -> triage
        for info in infos:
            if info.index >= len(p.calls):
                continue
            diff = self._signal_diff(info.signal)
            if diff:
                self.queue.push_triage(TriageItem(
                    prog=p.clone(), call_index=info.index, signal=diff,
                    origin=origin))
        return infos

    # ---- triage (reference triageInput fuzzer.go:521-625) ----

    def triage(self, item: TriageItem) -> None:
        with timed("fuzzer.triage", self._h_triage):
            self._triage(item)

    def _triage(self, item: TriageItem) -> None:
        """Sequential triage: the probe phase executes directly (one
        serial exec round-trip per probe, all on env 0 — the reference
        shape), then the acceptance phase lands the result."""
        res = self._triage_probe_phase(
            item,
            lambda p, stat, opts: self.execute(p, stat, opts,
                                               scan_new=False))
        if res is not None:
            self._finish_triage(item, *res)

    def _triage_probe_phase(self, item: TriageItem, executor):
        """The EXECUTION half of triage (reference triageInput
        fuzzer.go:521-625): stability reruns, signal intersection, and
        the minimize ladder — every execution goes through ``executor
        (prog, stat, opts) -> infos``, so the batched-bisection
        scheduler can rendezvous the probes into fleet-wide rounds
        while this per-item logic stays byte-for-byte the sequential
        algorithm (the minimized-program-identity guarantee).  Touches
        only thread-safe engine state (execute/stats); all acceptance
        mutations live in ``_finish_triage``.

        Returns ``None`` to drop the item (flaky/irrelevant signal) or
        ``(prog, call_index, inter, cover)``."""
        opts = ExecOpts(collect_signal=True, collect_cover=True)
        inter: Optional[Set[int]] = None
        cover: Set[int] = set()
        for _ in range(self.cfg.triage_reruns):
            infos = executor(item.prog, "exec_triage", opts)
            sig = self._call_signal(infos, item.call_index)
            if sig is None:
                continue
            cover.update(self._call_cover(infos, item.call_index) or ())
            inter = set(sig) if inter is None else (inter & set(sig))
            if not inter:
                return None  # flaky signal: drop
        if not inter:
            return None
        relevant = inter & set(item.signal) if item.signal else inter
        if item.signal and not relevant:
            return None

        def pred(p: Prog, call_index: int) -> bool:
            infos = executor(p, "exec_minimize", opts)
            sig = self._call_signal(infos, call_index)
            return sig is not None and relevant.issubset(set(sig))

        prog, call_index = item.prog, item.call_index
        if not item.minimized:
            prog, call_index = minimize(prog, call_index, pred)
        return prog, call_index, inter, cover

    def _finish_triage(self, item: TriageItem, prog: Prog,
                       call_index: int, inter: Set[int],
                       cover: Set[int]) -> None:
        """The ACCEPTANCE half of triage: signal/ledger/corpus/journal
        mutations, run on the scheduling thread only (and, for batched
        bisection, in queue order — so the corpus and attribution
        trajectories are identical to the sequential path's)."""
        item.prog, item.call_index = prog, call_index
        sig_list = sorted(inter)
        fresh = self._note_signal(sig_list)
        # credit the new signal (and, below, the corpus addition) to the
        # phase / operators that produced the input, not to the triage
        # step — and before the corpus dedup: a program that minimizes to
        # an already-known entry still contributed its fresh PCs, which
        # new_signal_total just counted
        origin = item.origin or Provenance(
            _attr.PHASE_CANDIDATE if item.from_candidate
            else _attr.PHASE_MUTATE)
        self._ledger.record_new_signal(origin.phase, origin.ops, fresh)
        if fresh:
            # event-sourced signal trajectory: each accepted new-signal
            # batch is one journal record with full provenance, so
            # replay() rebuilds new_signal_total bit-exactly offline
            self._jemit("signal", n=fresh, phase=origin.phase,
                        ops=list(origin.ops),
                        row=getattr(origin, "row", -1))
        # yield-weighted scheduling feedback: new signal (and, below,
        # the corpus addition) credits the arena row the candidate was
        # sampled from, so the on-device weighted draw favors proven
        # seeds and eviction spares them.  Accumulated into ONE credit
        # (one donated device write), stamp-guarded against the row
        # having been evicted+rewritten since the sample
        src = getattr(origin, "row", -1)
        credit = float(fresh)
        added = self._add_corpus(item.prog, sig_list)
        if added:
            credit += _CORPUS_ADD_CREDIT
        if credit > 0 and src >= 0 and self._device is not None:
            self._device.credit_row(src, credit,
                                    stamp=getattr(origin, "row_age", -1))
        if not added:
            return  # minimized to an already-known program
        self.stats["new_inputs"] += 1
        self._m_new_inputs.inc()
        self._ledger.record_corpus_add(origin.phase, origin.ops)
        self._jemit("corpus_add", phase=origin.phase,
                    ops=list(origin.ops), row=getattr(origin, "row", -1),
                    sig=len(sig_list),
                    h=hash_str(serialize(item.prog).encode())[:16])
        self._report_new_input(serialize(item.prog), item.call_index,
                               sig_list, sorted(cover))
        self.queue.push_smash(SmashItem(item.prog, item.call_index))

    def _triage_batch(self, items: List[TriageItem]) -> None:
        """Batched-bisection triage (ISSUE 8): run every queued item's
        rerun + minimize ladder CONCURRENTLY, with each probe execution
        rendezvoused into fleet-wide ROUNDS — one batch of probe
        programs per round, fanned across the executor fleet — instead
        of one serial exec round-trip per probe per item.  Minimize is
        just a candidate-execution schedule; the per-item decision
        ladder (prog/mutation.minimize) runs unmodified in its own
        worker, so each item's minimized program is byte-identical to
        what the sequential path produces on the same env.  Acceptance
        (_finish_triage) runs afterwards on this thread in queue
        order, so corpus/ledger/journal trajectories match the
        sequential path's ordering exactly."""
        if len(items) == 1:
            self.triage(items[0])
            return
        t0 = time.perf_counter()
        with span("fuzzer.triage_bisect"):
            outs = _BisectRounds(self, items).run()
        for item, res in zip(items, outs):
            if res is not None:
                self._finish_triage(item, *res)
        # keep the per-item latency series comparable with the
        # sequential path (which observes one triage per item)
        dt = (time.perf_counter() - t0) / len(items)
        for _ in items:
            self._h_triage.observe(dt)

    def _report_new_input(self, text: str, call_index: int,
                          signal: List[int], cover: List[int]) -> None:
        """Report a corpus addition to the manager; a manager outage must
        not kill the campaign (the input is already in the local corpus),
        so failures are logged + counted and the report is RETAINED —
        poll_manager re-sends the backlog once the manager is back."""
        try:
            self.manager.new_input(text, call_index, signal, cover)
        except Exception as e:
            count_error("rpc_new_input", e)
            self._pending_new_inputs.append(
                (text, call_index, signal, cover))
            dropped = len(self._pending_new_inputs) - 1024
            if dropped > 0:  # bound the backlog — but never silently
                count_error("rpc_new_input_dropped", RuntimeError(
                    f"{dropped} oldest new_input report(s) dropped, "
                    f"backlog full"))
                for _ in range(dropped):
                    self._pending_new_inputs.popleft()

    @staticmethod
    def _call_signal(infos: List[CallInfo], call_index: int
                     ) -> Optional[List[int]]:
        for info in infos:
            if info.index == call_index:
                # ipc pads calls the child never reached (executed=False,
                # errno=-1); treat those as "no result, retry" — not as
                # empty signal, which would make triage discard the input
                # on one flaky run (reference counts them as notexecuted)
                if not info.executed:
                    return None
                return info.signal
        return None

    @staticmethod
    def _call_cover(infos: List[CallInfo], call_index: int
                    ) -> Optional[List[int]]:
        for info in infos:
            if info.index == call_index:
                return info.cover
        return None

    # ---- smash (reference smashInput fuzzer.go:491-519) ----

    def smash(self, item: SmashItem) -> None:
        with timed("fuzzer.smash", self._h_smash):
            self._smash(item)

    def _smash(self, item: SmashItem) -> None:
        if self.cfg.collect_comps:
            self._hints_seed(item)
        if self.cfg.fault_injection and item.call_index >= 0:
            self._fail_call(item.prog, item.call_index)
        for i in range(self.cfg.smash_mutations):
            p = item.prog.clone()
            ops = mutate(p, self.rng, self.cfg.program_length,
                         ct=self.choice_table, corpus=self.corpus)
            self.execute(p, "exec_smash",
                         origin=Provenance(_attr.PHASE_SMASH, ops))

    def _fail_call(self, p: Prog, call_index: int) -> None:
        for nth in range(100):  # 0-based; executor adds 1
            opts = ExecOpts(fault_call=call_index, fault_nth=nth)
            infos = self.execute(p, "exec_smash", opts)
            info = next((i for i in infos if i.index == call_index), None)
            if info is None or not info.fault_injected:
                break

    def _hints_seed(self, item: SmashItem) -> None:
        """reference executeHintSeed (fuzzer.go:627): exec with comps,
        then exec every hint mutant.  With a device present the
        (arg value x comparison) join runs as one batched XLA kernel
        (ops/hints.py — BASELINE config[3]); the host CompMap walk is the
        fallback and the parity reference."""
        opts = ExecOpts(collect_signal=False, collect_comps=True)
        infos = self.execute(item.prog, "exec_hints", opts)
        if self._device is not None:
            self._device_hints(item.prog, infos)
            return
        comp_maps = []
        for i in range(len(item.prog.calls)):
            info = next((x for x in infos if x.index == i), None)
            comp_maps.append(CompMap.from_pairs(info.comps if info else ()))
        mutate_with_hints(item.prog, comp_maps,
                          lambda p: self.execute(p, "exec_hints"))

    def _device_hints(self, p: Prog, infos: List[CallInfo]) -> None:
        """Device hints join: every (site value, cast variant, comparison)
        of a call tested in one broadcast compare, then the deduped
        replacers applied as host mutants (reference prog/hints.go:33-207
        semantics, parity-pinned by tests/test_hints.py)."""
        import numpy as np

        from ..ops import hints as dhints
        from ..prog.generation import SPECIAL_INTS
        from ..prog.hints import _arg_occurrences, apply_hint, hint_sites

        U64 = (1 << 64) - 1
        special = np.asarray([v & U64 for v in SPECIAL_INTS], np.uint64)
        for ci, call in enumerate(p.calls):
            info = next((x for x in infos if x.index == ci), None)
            if info is None or not info.comps or \
                    call.meta is p.target.mmap_syscall:
                continue
            sites = hint_sites(call)
            if not sites:
                continue
            ops = np.asarray([a & U64 for a, _ in info.comps], np.uint64)
            cargs = np.asarray([b & U64 for _, b in info.comps], np.uint64)
            ok, rep = dhints.hint_matrix(
                np.asarray([s[3] for s in sites], np.uint64),
                ops, cargs, special)
            reps, valid = dhints.unique_replacers(ok, rep, max_out=16)
            reps = np.asarray(reps)
            valid = np.asarray(valid)
            self.stats["hints_device_joins"] = self.stats.get(
                "hints_device_joins", 0) + 1
            for si, (idx, kind, off, _val) in enumerate(sites):
                for k in np.nonzero(valid[si])[0]:
                    clone = p.clone()
                    apply_hint(_arg_occurrences(clone.calls[ci])[idx],
                               kind, off, int(reps[si, k]))
                    self.execute(clone, "exec_hints")

    # ---- device batch execution (the raw fast path) ----

    def _run_device_batch(self, batch) -> None:
        """Execute one device-mutated batch: raw exec streams go straight
        to the executor (no Prog trees); a row is only decoded when its
        signal is new and the program is worth triaging.  Fallback rows
        (sanitize-special calls / codec long tail) decode eagerly and take
        the regular execute() path."""
        with timed("device.batch_exec", self._h_device_batch):
            self._run_device_batch_inner(batch)

    def _get_drain_pool(self):
        if self._drain_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._drain_pool = ThreadPoolExecutor(
                max_workers=len(self.envs),
                thread_name_prefix="syztpu-drain")
        return self._drain_pool

    def _plan_prefixes(self, batch):
        """Build the prefix-tree execution schedule for one staged batch
        (ops/prefix.build_plan under a ``device.prefix_plan`` span).
        getattr-tolerant by design: batches without encoded tensors
        (host-fallback paths, test fakes) or with prefix scheduling off
        plan nothing and drain exactly like before."""
        if not self.cfg.prefix_schedule:
            return None
        enc = getattr(batch, "batch", None)
        if enc is None or len(batch) < 2:
            return None
        from ..ops import prefix as pfx

        with span("device.prefix_plan"):
            # only rows with emitted exec streams can continue; the
            # decode-fallback long tail drains ungrouped
            rows = [r for r in range(len(batch))
                    if batch.streams[r] is not None]
            try:
                plan = pfx.build_plan(
                    enc.call_id, enc.slot_val, enc.data, rows=rows,
                    min_group=self.cfg.prefix_min_group,
                    min_calls=self.cfg.prefix_min_calls)
            except Exception as e:
                count_error("prefix_plan", e)
                return None
        if not plan:
            return None
        # cost/benefit gate: on a continuation fleet the prefix jobs
        # cost real executor round trips, so a plan whose estimated
        # splice savings don't exceed that warm-up cost is worse than
        # no plan.  Fallback fleets never pay warm-ups (the grouping
        # only feeds the free triage-scan reuse), so they keep it.
        if plan.calls_saved_est <= 0 and any(
                getattr(e, "supports_continuation", False)
                for e in self.envs):
            return None
        return plan

    def _assign_prefix_jobs(self, plan, env_jobs, overflow,
                            workers) -> None:
        """Partition the plan's root subtrees across drain workers —
        env-AFFINE: every prefix job and suffix row of one tree lands on
        the env that will hold its continuation cache entries.
        Quarantined envs are passed over at assignment time.

        When the chosen env has no continuation support (the real
        executor today), there is no per-env cache to be affine TO —
        the memoized-signal triage reuse keys off the engine-global
        scanned-set — so its grouped rows go to the shared overflow
        deque instead: pinning them would serialize the drain (measured
        +30% per-batch drain time on the 4-env real fleet) for zero
        cache benefit, and no cache-warming round trip is ever paid."""
        children: Dict[int, List[int]] = {}
        roots: List[int] = []
        for nid, nd in enumerate(plan.nodes):
            if nd.parent < 0:
                roots.append(nid)
            else:
                children.setdefault(nd.parent, []).append(nid)
        healthy = set(self.supervisor.healthy_envs())
        cand = [k for k in workers if k in healthy] or list(workers)
        load = {k: 0 for k in cand}
        for root in roots:
            subtree = []
            stack = [root]
            while stack:
                nid = stack.pop()
                subtree.append(nid)
                stack.extend(children.get(nid, ()))
            k = min(cand, key=lambda q: (load[q], q))
            cont = getattr(self.envs[k], "supports_continuation", False)
            for nid in sorted(subtree):  # plan order: parents first
                if cont:
                    env_jobs[k].append(("prefix", nid))
                    load[k] += 1
                for r in plan.nodes[nid].rows:
                    if cont:
                        env_jobs[k].append(("row", r, nid, 0))
                        load[k] += 1
                    else:
                        overflow.append(("row", r, nid, 0))

    def _run_device_batch_inner(self, batch) -> None:
        """Drain one device batch across ALL executor envs under the
        prefix-tree schedule: grouped rows are env-affine (all children
        of a tree node drain to the env holding its continuation cache
        entry), ungrouped rows load-balance dynamically off a shared
        overflow deque, and idle workers steal row jobs from the longest
        peer queue (a stolen suffix row self-heals its memo on the new
        env at the cost of one full exec).

        The fan-out stays SUPERVISED (engine/supervisor.py): an exec
        failure records against the env (jittered-backoff restart,
        quarantine past the threshold) and the row is re-planned onto a
        surviving env via the overflow deque — rows execute exactly once
        on success and are only dropped (counted AND surfaced in the
        wire stats) after ``drain_max_attempts`` distinct attempts.
        When a worker's env is quarantined, its remaining ROW jobs are
        re-planned to the survivors; its prefix jobs are dropped (they
        are cache warmers for that env only — suffix rows self-heal).
        The LAST worker never leaves: it waits out backoff and relies on
        un-quarantine probes, so a fully-failed fleet still drains.

        Stat/ledger updates go through the locked ``_record_exec``
        helper; triage enqueue and corpus adds are already thread-safe;
        the signal mirror is folded ONCE per batch, on the calling
        thread, after the workers join."""
        n = len(batch)
        nworkers = max(min(len(self.envs), n), 1)
        plan = self._plan_prefixes(batch)
        overflow: deque = deque()  # ungrouped + re-planned row jobs
        env_jobs: List[deque] = [deque() for _ in range(nworkers)]
        grouped: Set[int] = set()
        if plan is not None:
            self._assign_prefix_jobs(plan, env_jobs, overflow,
                                     range(nworkers))
            grouped = set(plan.row_node)
        for row in range(n):
            if row not in grouped:
                overflow.append(("row", row, -1, 0))
        rows_lock = threading.Lock()
        active = [nworkers]  # workers still in their loop (rows_lock)
        sup = self.supervisor
        max_attempts = max(self.cfg.drain_max_attempts, 1)

        def stealable() -> bool:
            return any(job[0] == "row" for q in env_jobs for job in q)

        def take_job(env_idx: int):
            """rows_lock held: own affine queue first, then the shared
            overflow, then steal a ROW job from the tail of the longest
            peer queue that HAS one (prefix jobs are useless off their
            env — a queue of only warmers is no victim)."""
            if env_jobs[env_idx]:
                return env_jobs[env_idx].popleft()
            if overflow:
                return overflow.popleft()
            victim = max(
                (q for q in env_jobs
                 if any(j[0] == "row" for j in q)),
                key=len, default=None)
            if victim is None:
                return None
            skipped = []
            stolen = None
            while victim:
                item = victim.pop()
                if item[0] == "row":
                    stolen = item
                    break
                skipped.append(item)
            victim.extend(reversed(skipped))
            return stolen

        def dump_queue(env_idx: int) -> None:
            """rows_lock held: re-plan this env's remaining row jobs to
            the survivors; drop its prefix jobs (cache warmers)."""
            for job in env_jobs[env_idx]:
                if job[0] == "row":
                    overflow.append(job)
            env_jobs[env_idx].clear()

        def drain(env_idx: int):
            sigs: List[List[int]] = []
            done = 0
            left = False
            try:
                while True:
                    item = None
                    with rows_lock:
                        if not (env_jobs[env_idx] or overflow
                                or stealable()):
                            active[0] -= 1
                            left = True
                            return sigs, done
                        # acquire exactly once per iteration: it has
                        # side effects (probe grants, backoff reads)
                        if sup.acquire(env_idx):
                            item = take_job(env_idx)
                        elif not (overflow or stealable()) and \
                                all(j[0] == "prefix"
                                    for j in env_jobs[env_idx]):
                            # only droppable cache warmers remain and
                            # this env can't take one right now: drop
                            # them and leave — the last worker must
                            # never stall a whole batch drain waiting
                            # out backoff for jobs whose loss is free
                            env_jobs[env_idx].clear()
                            active[0] -= 1
                            left = True
                            return sigs, done
                        elif active[0] > 1 and \
                                sup.usable_elsewhere(env_idx):
                            # hand remaining work to the survivors; the
                            # check and the worker-count decrement are
                            # atomic so the LAST worker can never leave
                            # (it waits out backoff and relies on
                            # un-quarantine probes — otherwise two dying
                            # workers could each trust the other and
                            # strand the rows)
                            dump_queue(env_idx)
                            active[0] -= 1
                            left = True
                            return sigs, done
                    if item is None:
                        time.sleep(0.005)
                        continue
                    if item[0] == "prefix":
                        sig = self._drain_prefix(batch, plan, item[1],
                                                 env_idx)
                        done += 1
                        if sig:
                            sigs.append(sig)
                        continue
                    _, row, nid, attempts = item
                    node = plan.nodes[nid] if nid >= 0 else None
                    status, sig = self._drain_row(batch, row, env_idx,
                                                  node=node)
                    if status == "env_failure":
                        # charge the env only for a row's FIRST failure:
                        # a row that already failed elsewhere is evidence
                        # the program (the kind of input a fuzzer exists
                        # to find) is the problem, and re-charging it
                        # would quarantine healthy envs one by one
                        if attempts == 0:
                            sup.record_failure(env_idx)
                        with rows_lock:
                            if attempts + 1 < max_attempts:
                                overflow.append(
                                    ("row", row, nid, attempts + 1))
                            else:
                                self._note_dropped_row()
                        continue
                    if status == "ok":
                        sup.record_success(env_idx)
                    done += 1  # ok/skip/fail/hang all consume the row
                    if sig:
                        sigs.append(sig)
            finally:
                if not left:  # exception path: stop counting as active
                    with rows_lock:
                        dump_queue(env_idx)
                        active[0] -= 1

        results = []
        first_exc = None
        with span("device.batch_drain"):
            if nworkers == 1:
                results.append(drain(0))
            else:
                pool = self._get_drain_pool()
                # collect EVERY worker before propagating a failure: an
                # early re-raise would leave stragglers draining rows in
                # the background, and a retried step would then race a
                # fresh drain against them on the same envs
                for f in [pool.submit(drain, k) for k in range(nworkers)]:
                    try:
                        results.append(f.result())
                    except BaseException as e:  # noqa: BLE001
                        if first_exc is None:
                            first_exc = e
        self._g_drain_occupancy.set(
            sum(1 for _, done in results if done) / max(len(self.envs), 1))
        self._fold_batch_signal([s for sigs, _ in results for s in sigs])
        if first_exc is not None:
            raise first_exc

    def _note_dropped_row(self) -> None:
        """One drain row exhausted its retries: count it in the
        registry, in the supervisor's introspection, AND in the wire
        stats — /stats.json and the dashboard supervision table must
        show silent loss, not just /metrics."""
        self._m_rows_dropped.inc()
        self.supervisor.record_dropped()
        with self._stats_lock:
            self.stats["drain_rows_dropped"] = self.stats.get(
                "drain_rows_dropped", 0) + 1

    def _prefix_seen(self, h: int) -> bool:
        with self._prefix_scanned_lock:
            seen = h in self._prefix_scanned
            if seen:
                self._prefix_scanned.move_to_end(h)
            return seen

    def _claim_prefix_scan(self, h: int) -> bool:
        """Atomic test-and-claim of the novelty scan for a prefix hash:
        exactly ONE concurrent drain worker gets True (it must scan the
        prefix range and, on a failed decode, release via
        ``_release_prefix_scan`` so a sibling can rescue the group's
        coverage).  A separate check-then-mark would let two siblings
        both take the scan path and enqueue duplicate TriageItems."""
        with self._prefix_scanned_lock:
            if h in self._prefix_scanned:
                self._prefix_scanned.move_to_end(h)
                return False
            self._prefix_scanned[h] = True
            while len(self._prefix_scanned) > 4096:
                self._prefix_scanned.popitem(last=False)
            return True

    def _release_prefix_scan(self, h: int) -> None:
        with self._prefix_scanned_lock:
            self._prefix_scanned.pop(h, None)

    def _count_prefix_reuse(self, hit: bool) -> None:
        """Registry + wire-stat accounting for one grouped row: ``hit``
        when its memoized prefix was reused (continuation splice or
        fallback triage-signal reuse)."""
        (self._m_prefix_hits if hit else self._m_prefix_misses).inc()
        key = "prefix_hits" if hit else "prefix_misses"
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + 1

    def _scan_infos_for_triage(self, batch, row: int, infos, origin,
                               skip_prefix_calls: int = 0) -> bool:
        """Novelty-scan one execution's CallInfos and enqueue triage
        work.  ``skip_prefix_calls`` > 0 skips call indices
        1..skip_prefix_calls — the memoized-prefix reuse: that range was
        scanned once when the prefix hash first executed, so the
        new-signal test never re-parses known prefix coverage (the
        prelude mmap at index 0 is always scanned: it runs fresh).

        The scan itself is ONE fused merge+new pass (ISSUE 8,
        ops/cover.merge_and_new_host) over every call's signal against
        the max-signal SCREEN bitset instead of a per-signal python set
        walk: a clear bit PROVES novelty (the screen is a superset
        image of max_signal), so only flagged calls pay the exact host
        diff that names the novel PCs.  Two accepted proxy trades,
        both the shape the device admission gate already makes: a
        novel signal every one of whose bits collides with known
        signal is screened out (odds ~ screen occupancy on the 2^26
        default), and a call whose novelty is entirely claimed by an
        earlier call of the SAME execution defers to it (first-claim,
        like the prefix-scan dedup — the claimant's triage re-executes
        the program and re-enqueues anything real).

        Returns False when novel signal was found but the row failed to
        decode (the codec long tail) — the triage work was LOST, so the
        caller must NOT mark the prefix hash as scanned: a sibling's
        scan may still decode and rescue the group's coverage."""
        cand = [info for info in infos
                if not (1 <= info.index <= skip_prefix_calls)]
        if self._tri_bits is not None and len(cand) > 1:
            import numpy as np

            from ..ops import cover as _cover

            rows = [info.signal for info in cand]
            arr = self._pack_signal_rows(rows)
            if arr.shape[1]:
                _, mask, _ = _cover.merge_and_new_host(
                    self._tri_bits, arr)
                # a signal VALUE that wraps to the SENT sentinel packs
                # as padding and is invisible to the screen — force
                # such calls onto the exact path instead of silently
                # dropping their (unscreenable) novelty
                packed = (arr != np.uint32(0xFFFFFFFF)).sum(axis=1)
                cand = [info for k, (info, m) in enumerate(zip(cand,
                                                               mask))
                        if m or packed[k] < len(rows[k])]
        decoded = None
        ok = True
        for info in cand:
            diff = self._signal_diff(info.signal)
            if not diff:
                continue
            if decoded is None:
                decoded = batch.decode(row)
            if decoded is not None and info.index < len(decoded.calls):
                self.queue.push_triage(TriageItem(
                    prog=decoded.clone(), call_index=info.index,
                    signal=diff, origin=origin))
            else:
                ok = False
        return ok

    def _drain_prefix(self, batch, plan, nid: int, env_idx: int):
        """Execute one PREFIX JOB — the cache-warming execution of a
        tree node's shared prefix on its affine env, continuing from the
        parent node's memo when present.  Never retried: a failed
        prefix job costs the group only its warm start (suffix rows
        self-heal the memo via their full-exec fallback), so it carries
        no exactly-once obligation.  Returns the executed signal (for
        the mirror fold) or None."""
        node = plan.nodes[nid]
        stream = batch.streams[node.carrier]
        call_ids = batch.call_ids(node.carrier)
        if stream is None:
            return None  # carrier fell back to decode: nothing to warm
        env = self.envs[env_idx]
        parent = plan.nodes[node.parent] if node.parent >= 0 else None
        origin = Provenance(_attr.PHASE_MUTATE,
                            ops_from_mask(batch.op_mask(node.carrier)),
                            row=batch.src_row(node.carrier),
                            row_age=batch.src_age(node.carrier))
        try:
            with self.supervisor.guard(env_idx, env):
                res = env.exec_prefix(
                    ExecOpts(), stream, call_ids, node.n_calls,
                    node.hash,
                    parent_hash=parent.hash if parent else None,
                    parent_calls=parent.n_calls if parent else 0)
        except Exception as e:
            count_error("drain_exec", e)
            self.supervisor.record_failure(env_idx)
            return None
        if res is None:
            return None  # env has no fork point: nothing was executed
        _, infos, failed, hanged, saved = res
        if saved:
            # wire-stat mirror of prefix_calls_saved_total: the ipc
            # layer reports exactly what memoization skipped (parent
            # continuation OR an already-warm cross-batch memo)
            with self._stats_lock:
                self.stats["prefix_calls_saved"] = self.stats.get(
                    "prefix_calls_saved", 0) + int(saved)
        # a warm-up completes no program: separate accounting keeps
        # exec_total (and the ledger it feeds) comparable across
        # scheduling modes — the carrier's own suffix exec carries the
        # ledger credit exactly once
        self._m_prefix_jobs.inc()
        with self._stats_lock:
            self.stats["prefix_jobs"] = self.stats.get(
                "prefix_jobs", 0) + 1
        if failed:
            if not infos:
                self.supervisor.record_failure(env_idx)
            return None
        self.supervisor.record_success(env_idx)
        if hanged:
            return None
        # scan the shared prefix for novelty ONCE per group (atomic
        # claim — a warm recurring node from an earlier batch is
        # already covered; a failed decode releases the claim so a
        # sibling can rescue).  A nested node's prefix CONTAINS its
        # parent's — skip the range the parent's job already scanned,
        # or every child level would re-enqueue duplicate TriageItems
        # for it (max_signal only advances at triage time, so the diff
        # would fire again)
        if self._claim_prefix_scan(node.hash):
            skip = (parent.n_calls if parent is not None
                    and self._prefix_seen(parent.hash) else 0)
            if not self._scan_infos_for_triage(
                    batch, node.carrier, infos, origin,
                    skip_prefix_calls=skip):
                self._release_prefix_scan(node.hash)
        return sorted({s for info in infos for s in info.signal})

    def _drain_row(self, batch, row: int, env_idx: int, node=None):
        """Execute one batch row on env ``env_idx``; returns
        ``(status, signal)`` where status is one of

          ``ok``          — executed cleanly (signal feeds the mirror fold)
          ``skip``        — nothing to run (empty mutation / no decode /
                            oversized stream the env would deterministically
                            reject)
          ``fail``        — consumed without env attribution either way:
                            STATUS_FAILED from a LIVE executor (call
                            records present — a program property), or a
                            decode-fallback row whose execute() hides
                            the env outcome
          ``hang``        — the program hung; the env enforced its timeout
                            correctly, so this is not an env failure
          ``env_failure`` — the executor died (crash, injected kill,
                            watchdog interrupt — failed with NO call
                            records): the caller re-shards the row onto a
                            surviving env

        ``node`` (a prefix-tree PrefixNode) marks a grouped SUFFIX JOB:
        on a continuation-capable env the row executes as
        ``exec_suffix`` (memoized prefix spliced with a fresh suffix);
        on a fallback env it executes fully but skips the novelty
        re-scan of prefix calls already scanned under the node's hash.

        Runs on drain worker threads — only thread-safe state may be
        touched (see _run_device_batch_inner)."""
        origin = Provenance(_attr.PHASE_MUTATE,
                            ops_from_mask(batch.op_mask(row)),
                            row=batch.src_row(row),
                            row_age=batch.src_age(row))
        stream = batch.streams[row]
        if stream is None:
            p = batch.decode(row)
            if p is None:
                return "skip", None
            # fallback rows take the regular execute() path on this
            # worker's env (pid pins the env, keeping serialization);
            # execute() consumes failures internally, so these rows are
            # not re-sharded — they are the rare codec long tail.  The
            # watchdog still guards the call, but the status is "fail"
            # (consumed, NO success credit): execute() hides whether the
            # env died, and crediting success here would let a sick env
            # reset its failure streak on every fallback row
            with self.supervisor.guard(env_idx, self.envs[env_idx]):
                infos = self.execute(p, "exec_fuzz", pid=env_idx,
                                     origin=origin)
            return "fail", sorted(
                {s for info in infos or () for s in info.signal})
        call_ids = batch.call_ids(row)
        if len(call_ids) <= 1:
            return "skip", None  # mutation emptied the program
        from ..ipc import protocol as _P

        if len(stream) > _P.IN_SHM_SIZE:
            # the env rejects this deterministically while staying
            # healthy — charging/re-sharding it would indict good envs
            return "skip", None
        if self.cfg.log_programs:
            # crash attribution/repro parses these records from the
            # console log — raw streams must log like execute() does
            p = batch.decode(row)
            if p is not None:
                from ..utils.log import logf
                logf(0, "executing program %d:\n%s", env_idx, serialize(p))
        env = self.envs[env_idx]
        cont = node is not None and \
            getattr(env, "supports_continuation", False)
        hit: Optional[bool] = None
        try:
            with self.supervisor.guard(env_idx, env):
                if cont:
                    _, infos, failed, hanged, hit = env.exec_suffix(
                        ExecOpts(), stream, call_ids, node.n_calls,
                        node.hash)
                else:
                    _, infos, failed, hanged = env.exec_raw(
                        ExecOpts(), stream, call_ids)
        except Exception as e:
            count_error("drain_exec", e)
            return "env_failure", None
        self._record_exec("exec_fuzz", origin)
        if failed:
            # call records present => the executor is alive and replied
            # STATUS_FAILED (a program property); absent => it died
            # mid-request and the row deserves a surviving env
            return ("fail" if infos else "env_failure"), None
        if hanged:
            return "hang", None
        skip = 0
        claimed = False
        if node is not None:
            # the engine's scanned-set is the SINGLE authority for the
            # novelty-scan skip — an env-side memo hit only says calls
            # were spliced, not that their coverage was ever parsed
            # (the carrier's scan may have failed decode, or the memo
            # may predate this engine's scanned-set LRU window).  The
            # claim is atomic: exactly one concurrent sibling scans.
            claimed = self._claim_prefix_scan(node.hash)
            # metric: a continuation splice is a hit even when this
            # row also draws the (one) scan duty for the group
            self._count_prefix_reuse(bool(hit) if hit is not None
                                     else not claimed)
            if hit:  # wire-stat mirror of prefix_calls_saved_total
                with self._stats_lock:
                    self.stats["prefix_calls_saved"] = \
                        self.stats.get("prefix_calls_saved", 0) + \
                        node.n_calls
            if not claimed:
                skip = node.n_calls
        ok = self._scan_infos_for_triage(batch, row, infos, origin,
                                         skip_prefix_calls=skip)
        if claimed and not ok:
            # the claimed scan failed to decode: release so a sibling
            # (or a later batch) can rescue the group's prefix coverage
            self._release_prefix_scan(node.hash)
        return "ok", sorted({s for info in infos for s in info.signal})

    # ---- the loop ----

    def step(self) -> None:
        """One scheduling decision (one iteration of the reference's
        proc loop, fuzzer.go:256-328)."""
        self._iter += 1
        # The TPU candidate factory runs on a fixed cadence regardless of
        # queue pressure — it is the primary fuzz source, double-buffered so
        # a batch is always cooking while the fleet executes the last one.
        # A pipeline that degraded off the device (XLA step ladder
        # exhausted) is skipped — the host mutation path below takes over.
        if (self._device is not None and not self._device.degraded
                and self.corpus
                and self._iter % self.cfg.device_period == 0):
            batch = self._device.candidates(self.corpus)
            if batch is not None:
                self.stats["device_dropped_stale"] = self.stats.get(
                    "device_dropped_stale", 0) + batch.dropped
                self.stats["device_deduped"] = self.stats.get(
                    "device_deduped", 0) + batch.deduped
                # wire stat: the RPC deployment's manager folds these
                # into fleet_* counters, which the dashboard admission
                # panel falls back to when the engine is remote
                self.stats["device_admitted"] = self.stats.get(
                    "device_admitted", 0) + len(batch)
                if len(batch):
                    self.stats["device_batches"] += 1
                    self.stats["device_candidates"] += len(batch)
                    self._m_device_batches.inc()
                    self._m_device_candidates.inc(len(batch))
                    self._run_device_batch(batch)
                    return
                # fully-stale batch: fall through to regular queue work
        item = self.queue.pop()
        if isinstance(item, TriageItem):
            # batched-bisection minimize (ISSUE 8): drain the rest of
            # this priority class and run every item's ladder as
            # fleet-wide probe rounds
            batch = [item]
            if self.cfg.minimize_bisect and self.cfg.minimize_batch > 1:
                batch += self.queue.pop_triage_batch(
                    self.cfg.minimize_batch - 1,
                    from_candidate=item.from_candidate)
            if len(batch) > 1:
                self._triage_batch(batch)
            else:
                self.triage(item)
            return
        if isinstance(item, CandidateItem):
            self.execute(item.prog, "exec_candidate")
            return
        if isinstance(item, SmashItem):
            self.smash(item)
            return
        if not self.corpus or self._iter % self.cfg.generate_period == 0:
            # only the host generation is timed: the execute() round trip
            # is already measured by ipc_exec_latency_seconds
            with timed("fuzzer.generate", self._h_generate):
                p = generate(self.target, self.rng, self.cfg.program_length,
                             self.choice_table)
            self.execute(p, "exec_gen")
        else:
            p = self.corpus[self.rng.intn(len(self.corpus))].clone()
            ops = mutate(p, self.rng, self.cfg.program_length,
                         ct=self.choice_table, corpus=self.corpus)
            self.execute(p, "exec_fuzz",
                         origin=Provenance(_attr.PHASE_MUTATE, ops))

    def loop(self, iterations: int = 0, duration: float = 0.0) -> None:
        t0 = time.time()
        i = 0
        while True:
            if iterations and i >= iterations:
                break
            if duration and time.time() - t0 >= duration:
                break
            self.step()
            i += 1
            self.maybe_checkpoint()
            if self._leak is not None and \
                    self.stats["exec_total"] >= self._next_leak_scan:
                self._next_leak_scan = self.stats["exec_total"] + \
                    self.cfg.leak_period
                leaks = self._leak.scan()
                if leaks:
                    self.leak_reports.extend(leaks)
                    del self.leak_reports[:-100]
                    self.stats["leaks"] = self.stats.get("leaks", 0) + \
                        len(leaks)

    def poll_manager(self) -> None:
        """Exchange stats/new-signal with the manager (fuzzer.go:334-427).

        A failed sync is logged + counted (``errors_rpc_poll_total``; the
        transport-attempt counter ``rpc_errors_total`` is RemoteManager's
        and is not double-bumped here) and the un-synced ``new_signal``
        is RETAINED for the next poll — a manager restart costs one
        missed exchange, not the campaign.  Transport-level
        retry/backoff and restart-aware reconnect live in
        manager/rpc.RemoteManager; this is the last-resort engine-side
        net under it."""
        with self._stats_lock:
            stats = dict(self.stats)
        # wire-stat identity stamp: the manager pops the (string) id
        # before folding the numeric counters, keyed per engine so
        # restart-aware attribution can follow one engine across
        # processes; the ledger rides along as an absolute state the
        # manager keeps latest-wins per engine (proc-token-guarded so
        # an in-process fuzzer, whose credit already lives in the
        # shared process-global ledger, is never double-counted)
        stats["engine_id"] = self.engine_id
        try:
            _faults.fire("rpc.poll")
            r = self.manager.poll(stats, need_candidates=not self.corpus,
                                  new_signal=sorted(self.new_signal),
                                  ledger={"proc": _journal.PROC_TOKEN,
                                          "engine_id": self.engine_id,
                                          "state": self._ledger.state()})
        except Exception as e:
            count_error("rpc_poll", e)
            return
        for text in r.get("new_inputs", ()):
            self._add_corpus_text(text)
        for text in r.get("candidates", ()):
            self._push_candidate_text(text)
        self.max_signal.update(r.get("max_signal", ()))
        self._screen_note(r.get("max_signal", ()))
        self.new_signal.clear()
        # the manager is reachable again: drain the retained new_input
        # backlog (reports that failed while it was down)
        while self._pending_new_inputs:
            args = self._pending_new_inputs[0]
            try:
                self.manager.new_input(*args)
            except Exception as e:
                count_error("rpc_new_input", e)
                break  # still flaky: keep the rest for the next poll
            self._pending_new_inputs.popleft()

    # ---- checkpoint / resume (engine/checkpoint.py) ----

    def checkpoint_state(self) -> dict:
        """Everything a ``--resume`` run needs to continue bit-identically:
        host signal sets + the max-signal bitset mirror, the corpus, the
        seeded RNG stream, queued work, the attribution ledger, wire
        stats, and — when the device pipeline is live — the resident
        arena (rows + ring cursor), the sharded proxy bitset, and the
        device PRNG key.  Called from the scheduling thread only (no
        drain is in flight between steps)."""
        with self._lock:
            corpus = [serialize(p) for p in self.corpus]
            corpus_signal = sorted(self.corpus_signal)
        with self._stats_lock:
            stats = dict(self.stats)
        state = {
            "engine_id": self.engine_id,
            "stats": stats,
            "corpus": corpus,
            "corpus_signal": corpus_signal,
            "max_signal": sorted(self.max_signal),
            "new_signal": sorted(self.new_signal),
            "seed_rng": self.rng.rng.getstate(),
            "iter": self._iter,
            "queue": self._queue_state(),
            "ledger": self._ledger.state(),
            "max_bits": (self._max_bits.copy()
                         if self._max_bits is not None else None),
        }
        if self._device is not None and not self._device.degraded:
            # a degraded pipeline's device state is unreadable/stale by
            # definition — resume rebuilds the arena from the corpus
            state["device"] = self._device.checkpoint_state()
        return state

    def _queue_state(self) -> dict:
        items = self.queue.snapshot_items()

        def enc_triage(t: TriageItem) -> dict:
            return {"prog": serialize(t.prog), "call_index": t.call_index,
                    "signal": list(t.signal),
                    "from_candidate": t.from_candidate,
                    "minimized": t.minimized,
                    "origin": ((t.origin.phase, list(t.origin.ops),
                                getattr(t.origin, "row", -1),
                                getattr(t.origin, "row_age", -1))
                               if t.origin is not None else None)}

        return {
            "triage": [enc_triage(t)
                       for t in items["triage_candidate"] + items["triage"]],
            "candidate": [{"prog": serialize(c.prog),
                           "minimized": c.minimized}
                          for c in items["candidate"]],
            "smash": [{"prog": serialize(s.prog),
                       "call_index": s.call_index}
                      for s in items["smash"]],
        }

    def save_checkpoint(self, path: str = "") -> int:
        """Atomically write the engine checkpoint; returns payload bytes."""
        path = path or self.checkpoint_path
        if not path:
            raise ValueError(
                "no checkpoint path (set FuzzerConfig.workdir or pass one)")
        t0 = time.perf_counter()
        n = _ckpt.write_checkpoint(path, self.checkpoint_state())
        self._h_ckpt_write.observe(time.perf_counter() - t0)
        self._m_ckpt_writes.inc()
        self._last_ckpt_time = time.time()
        self._next_ckpt = time.monotonic() + max(
            self.cfg.checkpoint_interval, 0.0)
        with self._stats_lock:
            execs = self.stats.get("exec_total", 0)
            ni = self.stats.get("new_inputs", 0)
        self._jemit("checkpoint_save", bytes=n, execs=execs,
                    new_inputs=ni, signal=len(self.max_signal))
        if self._journal is not None:
            # checkpoint durability extends to the journal: everything
            # the checkpoint's trajectory claims is on disk too
            self._journal.sync()
        return n

    def maybe_checkpoint(self, force: bool = False) -> bool:
        """Periodic checkpoint gate, called from loop() between steps."""
        if not self.checkpoint_path:
            return False
        if not force and (self.cfg.checkpoint_interval <= 0
                          or time.monotonic() < self._next_ckpt):
            return False
        try:
            self.save_checkpoint()
        except Exception as e:
            # a full/readonly disk — or a sick accelerator raising from
            # device_get while gathering device state — must not kill
            # the campaign the supervision layer exists to keep alive
            count_error("checkpoint_write", e)
            self._next_ckpt = time.monotonic() + max(
                self.cfg.checkpoint_interval, 1.0)
            return False
        return True

    def restore(self, path: str = "") -> bool:
        """Load ``path`` (default: the configured checkpoint) into this
        fuzzer.  Any defect — corruption, truncation, incompatible shapes
        — is rejected with a logged + counted error and ``False``: the
        engine starts fresh instead of crashing or loading garbage."""
        path = path or self.checkpoint_path
        try:
            st = _ckpt.read_checkpoint(path)
        except _ckpt.CheckpointError as e:
            self._m_ckpt_rejected.inc()
            count_error("checkpoint_load", e)
            self._jemit("checkpoint_reject", reason=str(e)[:200])
            return False
        try:
            self._apply_checkpoint(st)
        except Exception as e:
            self._m_ckpt_rejected.inc()
            count_error("checkpoint_apply", e)
            self._jemit("checkpoint_reject", reason=str(e)[:200])
            return False
        self._m_ckpt_restores.inc()
        self._last_ckpt_time = time.time()
        with self._stats_lock:
            execs = self.stats.get("exec_total", 0)
            ni = self.stats.get("new_inputs", 0)
        # the restore marker lets replay() reconcile counter rewinds:
        # journal records postdating the restored checkpoint describe
        # work a kill threw away (the journal is a superset of the
        # checkpoint by design)
        self._jemit("checkpoint_restore", execs=execs, new_inputs=ni,
                    signal=len(self.max_signal),
                    ckpt_engine=str(st.get("engine_id", "")))
        return True

    def _apply_checkpoint(self, st: dict) -> None:
        """Two-phase restore: parse/validate EVERYTHING first (raising
        before any engine state mutates), then install.  A checkpoint
        from a different corpus format or device config fails in phase
        one and leaves the fresh engine untouched."""
        from ..prog.encoding import deserialize

        # -- phase 1: decode and validate --
        corpus: List[Prog] = []
        hashes: Set[str] = set()
        for text in st["corpus"]:
            p = deserialize(self.target, text)
            corpus.append(p)
            hashes.add(hash_str(serialize(p).encode()))
        qs = st.get("queue", {})
        triage_items = []
        for d in qs.get("triage", ()):
            origin = d.get("origin")
            triage_items.append(TriageItem(
                prog=deserialize(self.target, d["prog"]),
                call_index=int(d["call_index"]),
                signal=list(d["signal"]),
                from_candidate=bool(d.get("from_candidate")),
                minimized=bool(d.get("minimized")),
                origin=(Provenance(
                    origin[0], origin[1],
                    origin[2] if len(origin) > 2 else -1,
                    origin[3] if len(origin) > 3 else -1)
                        if origin else None)))
        cand_items = [CandidateItem(deserialize(self.target, d["prog"]),
                                    minimized=bool(d.get("minimized")))
                      for d in qs.get("candidate", ())]
        smash_items = [SmashItem(deserialize(self.target, d["prog"]),
                                 call_index=int(d["call_index"]))
                       for d in qs.get("smash", ())]
        max_bits = st.get("max_bits")
        if max_bits is not None and self._max_bits is not None:
            import numpy as np

            max_bits = np.asarray(max_bits, dtype=np.uint32).copy()
            if max_bits.shape != self._max_bits.shape:
                # a mirror from a different mirror_bits config would
                # fold hashes at the wrong modulus — reject, don't drift
                raise ValueError(
                    f"checkpoint max_bits shape {max_bits.shape} != "
                    f"configured {self._max_bits.shape}")
        corpus_signal = set(st["corpus_signal"])
        max_signal = set(st["max_signal"])
        new_signal = set(st["new_signal"])
        if not isinstance(st["stats"], dict):
            raise ValueError("checkpoint stats is not a dict")
        # probe the RNG state on a scratch instance: a schema-bad state
        # (e.g. a future writer that kept CKPT_VERSION) must fail here,
        # not after half the engine state is installed
        import random as _random

        _random.Random().setstate(st["seed_rng"])
        for rows in st.get("ledger", {}).values():
            for cell in (rows or {}).values():
                e, ns, ca = (int(x) for x in cell)  # arity + type check
        dev_state = st.get("device")

        # -- phase 2: install (device first: restore_state validates
        # shapes before mutating and is the only remaining fallible
        # step, so a failure still leaves the fresh engine untouched) --
        if self._device is not None:
            if dev_state is not None:
                self._device.restore_state(dev_state)
            else:
                # checkpoint from a host-only (or degraded) run: rebuild
                # the arena by re-encoding the restored corpus
                for p in corpus:
                    self._device.add_corpus(p)
        with self._lock:
            self.corpus = corpus
            self.corpus_hashes = hashes
            self.corpus_signal = corpus_signal
        self.max_signal = max_signal
        self.new_signal = new_signal
        if self._tri_bits is not None:
            # rebuild the triage novelty screen as the exact image of
            # the restored max_signal (a stale superset would screen
            # out signal the restored engine has never seen)
            self._tri_bits[:] = 0
            self._screen_note(max_signal)
        with self._stats_lock:
            self.stats.update(st["stats"])
        self.rng.rng.setstate(st["seed_rng"])
        self._iter = int(st.get("iter", 0))
        self._ledger.load_state(st.get("ledger", {}))
        if max_bits is not None and self._max_bits is not None:
            self._max_bits = max_bits
        self.queue = WorkQueue()
        for t in triage_items:
            self.queue.push_triage(t)
        for c in cand_items:
            self.queue.push_candidate(c)
        for s in smash_items:
            self.queue.push_smash(s)


class _BisectRounds:
    """The batched-bisection probe scheduler (ISSUE 8): N triage items'
    probe phases run in their own worker threads; every execution they
    request blocks in a rendezvous until ALL still-active items have a
    probe staged, then the whole round executes as ONE batch fanned
    across the executor fleet (each item is pinned to a HOME env for
    its entire rerun + minimize ladder, so its verdict stream is
    internally consistent and — at one env — byte-identical to the
    sequential path).  Rounds collapse the serial-round-trip count per
    minimized item from "every probe" to "every bisection step of the
    deepest item": the axis ``minimize_bisect_rounds_total`` counts and
    the bench's ``minimize_bisect`` config compares.

    An env death during a round costs that ITEM, not the campaign
    (``errors_minimize_bisect_total``): the supervision philosophy —
    the sequential path would instead have propagated and killed the
    scheduling loop with the item."""

    def __init__(self, fuzzer: "Fuzzer", items: List[TriageItem]):
        self.f = fuzzer
        self.items = items
        self._cond = threading.Condition()
        self._pending: Dict[int, tuple] = {}   # idx -> (prog, stat, opts)
        self._results: Dict[int, object] = {}  # idx -> infos | exception
        self._active = 0
        self._out: List[Optional[tuple]] = [None] * len(items)
        healthy = sorted(fuzzer.supervisor.healthy_envs()) or \
            list(range(len(fuzzer.envs)))
        self._home = [healthy[i % len(healthy)]
                      for i in range(len(items))]

    # ---- item-worker side ----

    def _exec(self, idx: int, prog: Prog, stat: str, opts: ExecOpts):
        with self._cond:
            self._pending[idx] = (prog, stat, opts)
            self._cond.notify_all()
            while idx not in self._results:
                self._cond.wait()
            res = self._results.pop(idx)
        if isinstance(res, BaseException):
            raise res
        return res

    def _worker(self, idx: int, item: TriageItem) -> None:
        try:
            self._out[idx] = self.f._triage_probe_phase(
                item,
                lambda p, stat, opts: self._exec(idx, p, stat, opts))
        except BaseException as e:  # noqa: BLE001 — contain per item
            count_error("minimize_bisect", e)
            self._out[idx] = None
        finally:
            with self._cond:
                self._active -= 1
                self._pending.pop(idx, None)
                self._cond.notify_all()

    # ---- driver side (the engine's scheduling thread) ----

    def run(self) -> List[Optional[tuple]]:
        threads = [threading.Thread(
            target=self._worker, args=(i, item), daemon=True,
            name=f"syztpu-bisect-{i}")
            for i, item in enumerate(self.items)]
        self._active = len(threads)
        for t in threads:
            t.start()
        pool = self.f._get_drain_pool() if len(self.f.envs) > 1 else None
        while True:
            with self._cond:
                # a round is ready when every still-active worker has
                # staged its next probe (finished workers left the set)
                while self._active > 0 and \
                        len(self._pending) < self._active:
                    self._cond.wait()
                if self._active == 0 and not self._pending:
                    break
                batch = list(self._pending.items())
                self._pending.clear()
            self._run_round(batch, pool)
        for t in threads:
            t.join()
        return self._out

    def _run_round(self, batch, pool) -> None:
        f = self.f
        f._m_bisect_rounds.inc()
        f._m_bisect_execs.inc(len(batch))
        with f._stats_lock:
            f.stats["minimize_rounds"] = f.stats.get(
                "minimize_rounds", 0) + 1
            f.stats["minimize_batch_execs"] = f.stats.get(
                "minimize_batch_execs", 0) + len(batch)
        groups: Dict[int, list] = {}
        for idx, job in batch:
            groups.setdefault(self._home[idx], []).append((idx, job))

        def run_env(env_idx: int, jobs):
            out = []
            for idx, (prog, stat, opts) in jobs:
                try:
                    infos = f.execute(prog, stat, opts, pid=env_idx,
                                      scan_new=False)
                except BaseException as e:  # noqa: BLE001
                    out.append((idx, e))
                else:
                    out.append((idx, infos))
            return out

        results = []
        if pool is None or len(groups) == 1:
            for env_idx, jobs in groups.items():
                results.extend(run_env(env_idx, jobs))
        else:
            for fu in [pool.submit(run_env, k, v)
                       for k, v in groups.items()]:
                results.extend(fu.result())
        with self._cond:
            self._results.update(results)
            self._cond.notify_all()


class _InflightSlot:
    """One launched-but-unconsumed device batch in the pipeline ring:
    the step's 8 output arrays (device arrays mid-flight; host numpy
    after a checkpoint restore), the arena age-stamp snapshot taken at
    launch (yield-credit guard), and the launch clock (retroactive
    device.step span endpoint)."""

    __slots__ = ("outs", "ages", "t0")

    def __init__(self, outs, ages, t0):
        self.outs = outs
        self.ages = ages
        self.t0 = t0


class _DevicePipeline:
    """Device-side candidate factory: keeps the encoded corpus RESIDENT on
    device (ops/arena.CorpusArena — append-once ring tensors, sampled with
    jnp.take inside the sharded step) and emits batches of device-mutated
    candidates through a depth-k in-flight ring
    (``FuzzerConfig.pipeline_depth``) so the TPU mutates batches N+1..N+k
    while the executor fleet runs batch N (SURVEY §7 hard part #3).
    Each launch is one asynchronous enqueue (jax dispatch never blocks),
    every launched output starts its device-to-host transfer immediately
    via ``copy_to_host_async``, and the drain consumes whichever
    in-flight batch's transfer completed first — stage, dispatch, and
    drain overlap instead of running lockstep.  Depth 1 restores the old
    double buffer exactly.

    The sample/mutate/fingerprint/new-signal/admission step is the
    SHARDED mesh step (parallel/mesh.make_arena_fuzz_step) over every
    visible device — data parallelism over candidates on the ``fuzz``
    axis, the word-sharded proxy signal bitset AND recent-hash Bloom
    filter on ``cover``, ICI collectives for fold and test.  One chip is
    just the 1-device mesh.  Row selection happens ON DEVICE from the
    arena's yield-weighted cumulative table (nothing per-row crosses the
    host boundary per launch), and two device-side gates fire BEFORE the
    host pays for emission/decode/execution: the ``fresh`` mask drops
    stale mutants (all call fingerprints already seen — the reference's
    SignalNew gate, pkg/cover/cover.go:104-117) and the ``admit`` mask
    drops duplicates (in-batch sort-and-compare + Bloom recent-hash
    test, ops/admission.py).  Triage-confirmed yield credits back to the
    sampled arena rows, closing the scheduling loop."""

    def __init__(self, target, cfg: FuzzerConfig, journal=None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..descriptions.tables import get_tables
        from ..ops.arena import CorpusArena
        from ..ops.dtables import build_device_tables
        from ..parallel import mesh as pmesh
        from ..prog.execgen import ExecGen
        from ..prog.tensor import ProgBatch, TensorFormat, encode_prog

        self._jax = jax
        # campaign-journal emit hook (the owning Fuzzer's _jemit); the
        # degradation ladder and admission resets are exactly the state
        # transitions the journal exists to make replayable
        self._jemit = journal or (lambda ev, **fields: None)
        self.tables = get_tables(target)
        self.fmt = TensorFormat.for_tables(
            self.tables, max_calls=cfg.program_length)
        self.dt = build_device_tables(self.tables, self.fmt)
        self._ProgBatch = ProgBatch
        self._encode_prog = encode_prog
        self._execgen = ExecGen(self.tables, self.fmt)
        self.mesh = pmesh.make_mesh()
        self.n_fuzz, self.n_cover = self.mesh.devices.shape
        # batch must divide the fuzz axis; round up
        self.B = -(-cfg.device_batch // self.n_fuzz) * self.n_fuzz
        self._k_probes = max(int(cfg.admission_probes), 1)
        self._bloom_decay = float(cfg.admission_bloom_decay)
        self._yield_decay = float(cfg.arena_yield_decay)
        # the arena weight table can only carry a real (row-sharded)
        # sharding when the capacity divides the fuzz axis; otherwise it
        # stays replicated (still correct — just no partitioned cumsum)
        self._arena_cap = max(int(cfg.arena_capacity), 1)
        self._shard_weights = (self._arena_cap % self.n_fuzz == 0)
        self._step, self._shardings = pmesh.make_arena_fuzz_step(
            self.mesh, self.dt, batch=self.B, k_probes=self._k_probes,
            shard_weights=self._shard_weights)
        # the sharded bitset mapping requires power-of-two total bits
        # (parallel/mesh._shard_index); round up like the host mirror does
        nbits = 1 << (cfg.mirror_bits - 1).bit_length()
        nwords = max(nbits // 32, 32 * self.n_cover)
        self._sig_shard = jax.device_put(
            jnp.zeros(nwords, jnp.uint32), self._shardings["signal"])
        # recent-hash admission Bloom filter (ops/admission.py), sharded
        # like the signal bitset and donated through the step
        bbits = 1 << (int(cfg.admission_bloom_bits) - 1).bit_length()
        self._bloom_words = max(bbits // 32, 32 * self.n_cover)
        self._bloom_bits = self._bloom_words * 32
        self._bloom = jax.device_put(
            jnp.zeros(self._bloom_words, jnp.uint32),
            self._shardings["bloom"])
        self._key = jax.random.PRNGKey(1)
        # depth-k in-flight ring: each slot holds one launched-but-not-
        # yet-consumed step's outputs plus the arena age stamps
        # snapshotted the instant it launched — the yield-credit guard
        # must compare against the ages the rows had AT SAMPLE TIME; a
        # consume-time read would return the stamp of whatever program
        # has since overwritten the row, letting the misattributed
        # credit pass the guard — and the launch clock for the
        # retroactive (overlapping) device.step trace span
        self.depth = max(int(cfg.pipeline_depth), 1)
        self._inflight: deque = deque()
        self._sig_words = nwords
        self.degraded = False  # ladder exhausted: host mutation path only
        self.target = target
        # device-resident encoded corpus: programs are encoded once on
        # add_corpus and stay on the chips; the launch path samples rows
        # on device, so there is no per-launch host re-stacking
        self.arena = CorpusArena(self._arena_cap, self.fmt,
                                 sharding=self._shardings["arena"],
                                 weights_sharding=self._shardings["weights"])

        # device-health gauges (ISSUE 2): read-on-demand callbacks, so a
        # /metrics or sampler tick always sees live state.  Buffer bytes
        # come from jax.live_arrays() — the process-wide live device
        # allocations, which on the 1-pipeline-per-process deployments is
        # the pipeline's working set.
        reg = get_registry()
        self._g_occupancy = reg.gauge(
            "device_batch_occupancy",
            help="fraction of the last device batch kept after the "
                 "on-device stale-candidate gate")
        # degradation ladder accounting (retry -> recompile -> host)
        self._c_step_retries = reg.counter(
            "device_step_retries_total",
            help="failed device fuzz steps retried in place")
        self._c_step_recompiles = reg.counter(
            "device_step_recompiles_total",
            help="device fuzz steps rebuilt (fresh jit) after a retry "
                 "also failed")
        self._c_degraded = reg.counter(
            "device_degraded_total",
            help="device pipelines that exhausted the degradation ladder "
                 "and fell back to the host mutation path")
        # device-side candidate admission (ISSUE 5): duplicates never
        # reach the executor fleet, and the Bloom decay policy is
        # auditable from the occupancy gauge
        self._c_deduped = reg.counter(
            "candidates_deduped_total",
            help="device-mutated candidates dropped by admission "
                 "(in-batch duplicate or recent-hash Bloom hit) before "
                 "any host exec was paid")
        self._c_admitted = reg.counter(
            "candidates_admitted_total",
            help="device-mutated candidates admitted to the executor "
                 "fleet after the on-device dedup gate")
        self._g_bloom_occ = reg.gauge(
            "admission_bloom_occupancy",
            help="fraction of recent-hash Bloom filter bits set (the "
                 "filter resets past admission_bloom_decay)")
        self._c_bloom_resets = reg.counter(
            "admission_bloom_resets_total",
            help="recent-hash Bloom filter decay resets")
        # depth-k ring accounting: in-flight occupancy is the pipeline's
        # health signal (a persistently sub-depth gauge means launches
        # can't keep ahead of the drain), stalls are the honest cost
        # counter the bench sweep reports alongside execs/sec
        self._g_inflight = reg.gauge(
            "device_pipeline_inflight",
            help="launched-but-unconsumed device batches in the depth-k "
                 "in-flight ring (pipeline_depth)")
        self._c_stalls = reg.counter(
            "device_pipeline_stalls_total",
            help="device-batch consumes that had to block on an "
                 "incomplete device-to-host transfer (no in-flight slot "
                 "was ready when the drain wanted one)")

        def _live_bytes():
            return sum(getattr(a, "nbytes", 0) for a in jax.live_arrays())

        reg.gauge(
            "device_live_buffer_bytes",
            help="bytes of live device arrays (jax.live_arrays)"
        ).set_fn(_live_bytes)

    def close(self) -> None:
        self.arena.close()

    def add_corpus(self, p: Prog) -> None:
        batch = self._ProgBatch.empty(self.fmt, 1)
        try:
            self._encode_prog(self.tables, self.fmt, p, batch, 0)
        except Exception as e:
            # long-tail arg the tensor format can't carry yet — count it
            # so a codec regression shows as a rate, not silence
            count_error("device_encode", e)
            return
        self.arena.append(batch.call_id[0], batch.slot_val[0],
                          batch.data[0])

    def _launch(self):
        """One device launch behind the degradation ladder: on an XLA/JIT
        step failure retry once in place, then rebuild the jitted step
        (recompile), then permanently fall back to the host mutation
        path (``degraded`` — mirroring bench.py's cpu-fallback), counting
        ``device_degraded_total``.  The campaign survives a sick
        accelerator at reduced throughput instead of dying with it."""
        if self.degraded:
            return None
        if len(self.arena) == 0:
            return None
        from ..parallel import mesh as pmesh

        for rung in ("try", "retry", "recompile"):
            try:
                if rung == "recompile":
                    self._c_step_recompiles.inc()
                    self._jemit("device_degrade", rung="recompile")
                    self._step, self._shardings = \
                        pmesh.make_arena_fuzz_step(
                            self.mesh, self.dt, batch=self.B,
                            k_probes=self._k_probes,
                            shard_weights=self._shard_weights,
                            fresh=True)
                return self._launch_once()
            except Exception as e:
                count_error("device_step", e)
                self._heal_inflight()
                if rung == "try":
                    self._c_step_retries.inc()
                    self._jemit("device_degrade", rung="retry")
        self.degraded = True
        self._c_degraded.inc()
        self._jemit("device_degrade", rung="host_fallback")
        from ..utils.log import logf

        logf(0, "device pipeline degraded to host mutation path "
                "(step failed after retry + recompile)")
        return None

    def _launch_once(self):
        jax = self._jax
        # nothing per-row crosses the host->device boundary per launch:
        # row selection draws from the yield-weighted cumulative table ON
        # DEVICE, the batch is gathered out of the resident arena with
        # jnp.take inside the jitted sharded step, and the signal bitset
        # + admission Bloom filter update in place (donated).  The step
        # reports which rows it drew (idx -> yield credit) and its
        # admission verdict per mutant.
        with span("device.batch_stage"):
            _faults.fire("device.step")
            self._key, kstep = jax.random.split(self._key)
            a_cid, a_sval, a_data = self.arena.tensors()
            weights = self.arena.weights_tensor()
            (idx, cid, sval, data, self._sig_shard, self._bloom, fresh,
             admit, op_mask, bloom_pop) = self._step(
                kstep, a_cid, a_sval, a_data, weights, self._sig_shard,
                self._bloom)
        return idx, cid, sval, data, fresh, admit, op_mask, bloom_pop

    def _reset_bloom(self) -> None:
        """Decay the recent-hash filter to empty (the periodic reset that
        bounds its false-positive rate)."""
        import jax.numpy as jnp

        self._bloom = self._jax.device_put(
            jnp.zeros(self._bloom_words, jnp.uint32),
            self._shardings["bloom"])

    def _heal_donated_buffers(self) -> None:
        """A failed step may have consumed the donated proxy bitset and
        admission Bloom filter; rebuild whichever died before the next
        rung.  Conservative: lost proxy/filter state only means some
        stale or duplicate candidates re-test as fresh — extra host
        work, never lost coverage (the exact sets live on the host)."""
        jax = self._jax
        import jax.numpy as jnp

        def healed(buf, words, sharding):
            try:
                deleted = bool(buf.is_deleted())
            except Exception:
                deleted = False  # no introspection: assume still live
            if not deleted:
                return buf
            return jax.device_put(jnp.zeros(words, jnp.uint32), sharding)

        self._sig_shard = healed(self._sig_shard, self._sig_words,
                                 self._shardings["signal"])
        self._bloom = healed(self._bloom, self._bloom_words,
                             self._shardings["bloom"])

    def _heal_inflight(self) -> None:
        """After a step failure, heal EVERY piece of device state the
        failure may have poisoned — not just the newest launch's donated
        buffers.  With depth-k batches in flight, the failed step's
        donated sig/bloom inputs were the OUTPUTS of an earlier launch,
        and a mid-flight device failure can kill buffers belonging to
        ANY staged slot; a slot whose outputs died must be dropped (its
        eventual drain would just raise again) while healthy older slots
        keep their staged candidates.  The pre-pipeline code healed only
        self._sig_shard/self._bloom and assumed the single pending batch
        was still live — at depth>1 that left poisoned slots to blow up
        the consume path later."""
        self._heal_donated_buffers()
        kept: deque = deque()
        dropped = 0
        for slot in self._inflight:
            dead = False
            for x in slot.outs:
                try:
                    if bool(x.is_deleted()):
                        dead = True
                        break
                except Exception:
                    continue  # host array / no introspection: live
            if dead:
                dropped += 1
            else:
                kept.append(slot)
        self._inflight = kept
        if dropped:
            self._jemit("device_inflight_dropped", slots=dropped)
        self._g_inflight.set(len(self._inflight))

    # read-only single-slot views of the ring, kept for tests/tools
    # written against the old double buffer: the OLDEST staged batch is
    # what "the pending batch" used to mean (next to be consumed)

    @property
    def _pending(self):
        return self._inflight[0].outs if self._inflight else None

    @property
    def _pending_ages(self):
        return self._inflight[0].ages if self._inflight else None

    def _fill(self) -> None:
        """Top the in-flight ring up to pipeline depth.  Each launch is
        one asynchronous enqueue behind the degradation ladder, and
        every output immediately starts its device-to-host transfer
        (``copy_to_host_async`` per array) so the drain later finds the
        bytes already on the host instead of paying the D2H latency
        synchronously."""
        while (not self.degraded and len(self.arena) > 0
               and len(self._inflight) < self.depth):
            t0 = time.perf_counter()
            outs = self._launch()
            if outs is None:
                break
            for x in outs:
                try:
                    x.copy_to_host_async()
                except AttributeError:
                    pass  # restored host array: already on the host
            # snapshot the age stamps the instant the batch launches
            # (same thread: no append can interleave) — these are the
            # sample-time stamps its eventual yield credits must carry
            self._inflight.append(
                _InflightSlot(outs, self.arena.ages.copy(), t0))
        self._g_inflight.set(len(self._inflight))

    def _take_ready(self) -> "_InflightSlot":
        """Pop the first in-flight slot whose transfers have all landed
        (restored host arrays count as landed); when none is ready yet
        the drain is about to block on an incomplete transfer — count
        the stall and take the oldest so consume order stays FIFO under
        pressure."""
        for i, slot in enumerate(self._inflight):
            ready = True
            for x in slot.outs:
                is_ready = getattr(x, "is_ready", None)
                if is_ready is not None and not is_ready():
                    ready = False
                    break
            if ready:
                del self._inflight[i]
                return slot
        self._c_stalls.inc()
        return self._inflight.popleft()

    def credit_row(self, row: int, amount: float,
                   stamp: int = -1) -> None:
        """Feed triage-confirmed yield (new-signal PCs, corpus adds)
        back to the arena row the candidate was sampled from — the
        weighted scheduler's feedback edge.  ``stamp`` is the row's age
        at sample time; a mismatch means the row was evicted since and
        the credit is dropped."""
        self.arena.credit(row, amount, stamp=stamp)

    def candidates(self, corpus: List[Prog]) -> Optional["_DeviceBatch"]:
        """Consume the first READY in-flight batch — raw exec streams
        with a lazy per-row decoder — and refill the launch ring.

        The ring is topped up to ``pipeline_depth`` before and after the
        consume, so at steady state the device is always mutating k
        batches ahead of the executor drain; on a cold start (ring
        empty) the just-launched work is left in flight and None is
        returned rather than stalling the host on it.  Stale rows
        (fresh mask false) and admission-rejected rows (in-batch
        duplicates, recent-hash Bloom hits) are dropped here, before the
        host pays for emission or an executor round-trip; the fast host
        boundary (prog/execgen.py) then emits executor wire bytes
        straight from the tensors (~20x the decode_prog walk), and a
        Prog tree is only materialized for rows the engine actually
        wants to triage."""
        import numpy as np

        was_empty = not self._inflight
        self._fill()
        if was_empty or not self._inflight:
            # warm-up (or degraded/empty arena): the batches just
            # launched stay in flight — consuming one now would block
            # the host on it, exactly the lockstep the ring removes
            return None
        slot = self._take_ready()
        try:
            # the one host sync per consume: materializing np arrays
            # blocks until the slot's D2H transfer lands (already
            # complete unless _take_ready counted a stall)
            with span("device.fuzz_step.sync"):
                arrs = [np.asarray(x) for x in slot.outs]
        except Exception as e:
            # transfer surfaced a device failure post-launch: count it,
            # heal what died (dropping any other poisoned slots), and
            # skip this consume — the campaign continues
            count_error("device_step", e)
            self._c_step_retries.inc()
            self._jemit("device_degrade", rung="consume_retry")
            self._heal_inflight()
            self._fill()
            return None
        # the honest overlapping trace record: launch -> consume per
        # slot, so at depth>=2 the device.step spans overlap and their
        # sum can exceed the wall time of the drain loop
        record_span("device.step", slot.t0, time.perf_counter())
        self._fill()  # replace the consumed slot before the host drains
        done_ages = slot.ages
        (idx, cid, sval, data, fresh, admit,
         op_mask, bloom_pop) = arrs
        fresh = fresh.astype(bool)
        admit = admit.astype(bool)
        total = int(cid.shape[0])
        stale = int(np.count_nonzero(~fresh))
        deduped = int(np.count_nonzero(fresh & ~admit))
        keep = np.nonzero(fresh & admit)[0]
        self._g_occupancy.set(keep.size / total if total else 0.0)
        if deduped:
            self._c_deduped.inc(deduped)
        if keep.size:
            self._c_admitted.inc(int(keep.size))
        # Bloom decay: reset once the filter saturates past the target
        # occupancy (FP rate ~ occupancy**k — at 0.5 with k=4 that is
        # ~6%, each FP costing only one skipped-but-novel candidate)
        occ = float(bloom_pop) / float(self._bloom_bits)
        self._g_bloom_occ.set(occ)
        if occ >= self._bloom_decay:
            self._reset_bloom()
            self._c_bloom_resets.inc()
            # age-decay the arena yield scores on the same occupancy
            # cadence: early-campaign jackpot rows must keep earning to
            # keep their weighted-sampler pin (ROADMAP carried item)
            self.arena.decay_yields(self._yield_decay)
            self._jemit("bloom_reset", occupancy=round(occ, 4),
                        yield_decay=self._yield_decay)
        if keep.size < total:
            cid, sval, data = cid[keep], sval[keep], data[keep]
            op_mask, idx = op_mask[keep], idx[keep]
        batch = self._ProgBatch(call_id=cid, slot_val=sval, data=data)
        streams = self._execgen.emit_batch(batch)
        return _DeviceBatch(self, batch, streams, dropped=stale,
                            deduped=deduped, op_masks=op_mask,
                            src_rows=idx,
                            src_ages=(done_ages[idx]
                                      if done_ages is not None else None))

    # ---- checkpoint round-trip (engine/checkpoint.py) ----

    def checkpoint_state(self) -> dict:
        """Device-resident state a resume must restore bit-identically:
        the corpus arena (rows + ring cursor/size/evictions + yield
        scores/ages), the sharded proxy signal bitset, the admission
        Bloom filter, the device PRNG key, and — so resume never
        re-mutates batches of work — ALL k in-flight candidate batches
        (staged rows, pre-compaction, oldest first) each with its
        launch-time age-stamp snapshot.  Pulling a mid-flight batch to
        the host here forces its transfer; that is the price of an
        exact checkpoint, paid only on the checkpoint cadence."""
        import numpy as np

        jax = self._jax
        a_cid, a_sval, a_data = self.arena.tensors()
        inflight = [{
            "outs": [np.asarray(jax.device_get(x)) for x in slot.outs],
            "ages": (slot.ages.copy() if slot.ages is not None else None),
        } for slot in self._inflight]
        return {
            "arena": {
                "cid": np.asarray(jax.device_get(a_cid)),
                "sval": np.asarray(jax.device_get(a_sval)),
                "data": np.asarray(jax.device_get(a_data)),
                "size": self.arena.size,
                "cursor": self.arena.cursor,
                "evictions": self.arena.evictions,
                "weighted_evictions": self.arena.weighted_evictions,
                "yields": self.arena.yields.copy(),
                "ages": self.arena.ages.copy(),
                "seq": self.arena._seq,
            },
            "sig_shard": np.asarray(jax.device_get(self._sig_shard)),
            "bloom": np.asarray(jax.device_get(self._bloom)),
            "key": np.asarray(jax.device_get(self._key)),
            "inflight": inflight,
        }

    def validate_state(self, st: dict) -> None:
        """Raise before any restore mutation if the checkpoint's device
        shapes don't match this pipeline's config (different
        arena_capacity / mirror_bits / program_length)."""
        import numpy as np

        ar = st["arena"]
        a_cid, a_sval, a_data = self.arena.tensors()
        for name, got, want in (("cid", ar["cid"], a_cid),
                                ("sval", ar["sval"], a_sval),
                                ("data", ar["data"], a_data)):
            if tuple(np.shape(got)) != tuple(want.shape):
                raise ValueError(
                    f"checkpoint arena {name} shape {np.shape(got)} != "
                    f"configured {tuple(want.shape)}")
        if tuple(np.shape(st["sig_shard"])) != \
                tuple(self._sig_shard.shape):
            raise ValueError(
                f"checkpoint sig_shard shape {np.shape(st['sig_shard'])} "
                f"!= configured {tuple(self._sig_shard.shape)}")
        bloom = st.get("bloom")
        if bloom is not None and \
                tuple(np.shape(bloom)) != tuple(self._bloom.shape):
            raise ValueError(
                f"checkpoint bloom shape {np.shape(bloom)} != "
                f"configured {tuple(self._bloom.shape)}")
        pending = st.get("pending")
        if pending is not None and len(pending) != 8:
            raise ValueError(
                f"checkpoint pending batch has {len(pending)} fields, "
                f"expected 8")
        for i, slot in enumerate(st.get("inflight") or ()):
            outs = slot.get("outs")
            if outs is None or len(outs) != 8:
                raise ValueError(
                    f"checkpoint inflight slot {i} has "
                    f"{0 if outs is None else len(outs)} fields, "
                    f"expected 8")

    def restore_state(self, st: dict) -> None:
        import numpy as np
        import jax.numpy as jnp

        jax = self._jax
        self.validate_state(st)
        ar = st["arena"]
        self.arena.restore(
            ar["cid"], ar["sval"], ar["data"],
            size=int(ar["size"]), cursor=int(ar["cursor"]),
            evictions=int(ar.get("evictions", 0)),
            weighted_evictions=int(ar.get("weighted_evictions", 0)),
            yields=ar.get("yields"), ages=ar.get("ages"),
            seq=int(ar.get("seq", 0)))
        self._sig_shard = jax.device_put(
            jnp.asarray(np.asarray(st["sig_shard"], np.uint32)),
            self._shardings["signal"])
        bloom = st.get("bloom")
        if bloom is not None:
            self._bloom = jax.device_put(
                jnp.asarray(np.asarray(bloom, np.uint32)),
                self._shardings["bloom"])
        else:
            self._reset_bloom()  # pre-admission checkpoint: start empty
        self._key = jnp.asarray(st["key"])
        # (older checkpoints carry a "pick" host-RNG state from when row
        # selection happened host-side; selection is on-device now, so
        # the key is simply ignored)
        # the in-flight batches: restoring them (oldest first) means
        # resume continues with the EXACT candidates that were staged
        # when the checkpoint was written, instead of re-mutating up to
        # k batches of work (host numpy is fine here — candidates()
        # materializes with np.asarray either way, and host arrays
        # always test ready so restored slots drain deterministically in
        # checkpoint order), each with its launch-time age stamps so
        # yield credits stay guarded across the restart
        def _host_slot(outs, ages):
            return _InflightSlot(
                tuple(np.asarray(x) for x in outs),
                (np.asarray(ages, np.int64).copy()
                 if ages is not None else None),
                time.perf_counter())

        self._inflight = deque()
        for slot in st.get("inflight") or ():
            self._inflight.append(
                _host_slot(slot["outs"], slot.get("ages")))
        # pre-pipeline checkpoints staged at most one batch ("pending")
        pending = st.get("pending")
        if not self._inflight and pending is not None:
            self._inflight.append(
                _host_slot(pending, st.get("pending_ages")))
        self._g_inflight.set(len(self._inflight))


class _DeviceBatch:
    """One device-mutated candidate batch: raw exec streams (None where the
    row needs the decode fallback) plus lazy row decoding for triage."""

    def __init__(self, pipe: "_DevicePipeline", batch, streams,
                 dropped: int = 0, deduped: int = 0, op_masks=None,
                 src_rows=None, src_ages=None):
        import numpy as np

        self.pipe = pipe
        self.batch = batch
        self.streams = streams
        self.dropped = dropped  # stale rows gated off on device
        self.deduped = deduped  # duplicate rows gated off by admission
        self.op_masks = op_masks  # [B] u32 per-row operator provenance
        self.src_rows = src_rows  # [B] i32 arena row each mutant came from
        self.src_ages = src_ages  # [B] i64 row age stamps (credit guard)
        self._decoded: Dict[int, Optional[Prog]] = {}
        # per-row stream call ids, vectorized once for the whole batch:
        # one numpy mask + one C-level tolist over [B, C] instead of a
        # per-row per-int Python conversion loop (built eagerly so the
        # parallel drain workers read an immutable list)
        cid = np.asarray(batch.call_id)
        live = cid >= 0
        flat = cid[live].tolist()
        mm = pipe.target.mmap_syscall.id
        rows: List[List[int]] = []
        start = 0
        for end in np.cumsum(live.sum(axis=1)).tolist():
            rows.append([mm] + flat[start:end])
            start = end
        self._call_ids = rows

    def __len__(self) -> int:
        return len(self.streams)

    def op_mask(self, row: int) -> int:
        """Mutation-operator bitmask for one row (0 when the pipeline ran
        without provenance tracking)."""
        if self.op_masks is None:
            return 0
        return int(self.op_masks[row])

    def src_row(self, row: int) -> int:
        """Arena row this candidate was sampled from (-1 when the batch
        carries no sampling provenance) — the yield-credit target."""
        if self.src_rows is None:
            return -1
        return int(self.src_rows[row])

    def src_age(self, row: int) -> int:
        """Age stamp of the source arena row at consume time (-1 without
        provenance) — CorpusArena.credit drops stale-stamp credits."""
        if self.src_ages is None:
            return -1
        return int(self.src_ages[row])

    def call_ids(self, row: int) -> List[int]:
        """Stream call ids: prelude mmap + the row's active calls (matches
        both the emitted stream and the decoded Prog's call list).
        Precomputed for the whole batch in __init__."""
        return self._call_ids[row]

    def decode(self, row: int) -> Optional[Prog]:
        if row in self._decoded:
            return self._decoded[row]
        from ..prog.tensor import decode_prog

        p: Optional[Prog] = None
        try:
            # decode_prog runs assign_sizes_call + sanitize_call per call
            p = decode_prog(self.pipe.tables, self.pipe.fmt,
                            self.batch, row)
        except Exception as e:
            # codec long tail: the row still executed as a raw stream,
            # only triage loses it — count so regressions are visible
            count_error("device_decode", e)
            p = None
        self._decoded[row] = p
        return p
