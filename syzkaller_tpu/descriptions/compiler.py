"""Compiler: description AST -> typed Target.

Performs what the reference splits across pkg/compiler (check/consts/gen:
/root/reference/pkg/compiler/compiler.go:45) and syz-sysgen: const
resolution, type instantiation per use-direction, struct layout (natural
alignment padding, bitfield grouping, packed/align attributes), resource
kind chains, and syscall-number binding. Instead of emitting generated Go
source like sysgen, the result is a live `Target`; the flat numpy tables the
TPU kernels index are derived from it in `.tables`.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Dict, List, Optional, Tuple, Union

from ..prog.target import Target
from ..prog.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumKind,
    CsumType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceDesc,
    ResourceType,
    StructType,
    Syscall,
    TextKind,
    Type,
    UnionType,
    VmaType,
)
from . import ast
from .ast import (
    CallDef,
    DefineDef,
    Description,
    FlagsDef,
    Ident,
    IntLit,
    IntRange,
    ResourceDef,
    StrFlagsDef,
    StrLit,
    StructDef,
    TypeExpr,
)

PSEUDO_NR_BASE = 1 << 30  # syz_* pseudo-syscalls

# Fixed pseudo-syscall ids, mirrored by the executor's execute_pseudo
# dispatch (executor.cc kSyz* constants).  Fixed (not appearance-ordered)
# so description reshuffles can't silently retarget the C++ side.
PSEUDO_IDS = {
    "syz_open_dev": 0,
    "syz_open_pts": 1,
    "syz_emit_ethernet": 2,
    "syz_extract_tcp_res": 3,
    "syz_fuse_mount": 4,
    "syz_fusectl_mount": 5,
    "syz_kvm_setup_cpu": 6,
    "syz_test": 7,
}
_PSEUDO_DYN_BASE = 64  # unknown syz_* calls: stable sorted allocation

_INT_SIZES = {"int8": 1, "int16": 2, "int32": 4, "int64": 8,
              "int16be": 2, "int32be": 4, "int64be": 8}

_TEXT_KINDS = {"x86_real": TextKind.X86_REAL, "x86_16": TextKind.X86_16,
               "x86_32": TextKind.X86_32, "x86_64": TextKind.X86_64,
               "arm64": TextKind.ARM64}

_DIRS = {"in": Dir.IN, "out": Dir.OUT, "inout": Dir.INOUT}


class CompileError(Exception):
    pass


class Compiler:
    def __init__(self, desc: Description, consts: Dict[str, int], *,
                 os: str = "linux", arch: str = "amd64", ptr_size: int = 8,
                 page_size: int = 4096):
        self.desc = desc
        self.consts = dict(consts)
        self.os = os
        self.arch = arch
        self.ptr_size = ptr_size
        self.page_size = page_size

        self.resources: Dict[str, ResourceDef] = {}
        self.structs: Dict[str, StructDef] = {}
        self.flags: Dict[str, FlagsDef] = {}
        self.strflags: Dict[str, StrFlagsDef] = {}
        self.calls: List[CallDef] = []
        self.warnings: List[str] = []
        self.unsupported: List[str] = []

        self._struct_memo: Dict[Tuple[str, Dir], Type] = {}
        # (name, dir) -> copies handed out while the struct is mid-build;
        # patched in place when its layout completes (recursive descriptions).
        self._struct_pending: Dict[Tuple[str, Dir], list] = {}
        self._res_desc_memo: Dict[str, ResourceDesc] = {}

    # ------------------------------------------------------------------ #

    def compile(self) -> Target:
        self._index_nodes()
        self._resolve_defines()

        resources: List[ResourceDesc] = []
        for name in self.resources:
            resources.append(self._resource_desc(name))

        syscalls: List[Syscall] = []
        seen_calls: Dict[str, str] = {}
        dyn_pseudo = sorted({cd.call_name for cd in self.calls
                             if cd.call_name.startswith("syz_")
                             and cd.call_name not in PSEUDO_IDS})
        for cd in self.calls:
            try:
                args = tuple(
                    self._make_type(f.typ, Dir.IN, f.name, is_arg=True)
                    for f in cd.fields)
                ret: Optional[Type] = None
                if cd.ret is not None:
                    rt = self._make_type(cd.ret, Dir.OUT, "ret", is_arg=True)
                    if isinstance(rt, ResourceType):
                        ret = rt
                    # non-resource returns carry no dataflow: drop them
            except _SkipCall as e:
                self.unsupported.append(f"{cd.name}: {e}")
                continue
            if cd.call_name.startswith("syz_"):
                pid = PSEUDO_IDS.get(cd.call_name)
                if pid is None:
                    pid = _PSEUDO_DYN_BASE + dyn_pseudo.index(cd.call_name)
                nr = PSEUDO_NR_BASE + pid
            else:
                nr = self.consts.get(f"__NR_{cd.call_name}")
                if nr is None:
                    self.unsupported.append(f"{cd.name}: no __NR_{cd.call_name}")
                    continue
            if cd.name in seen_calls:
                # duplicate full names (same base$variant) make text
                # deserialization ambiguous; the reference's compiler
                # rejects them too (pkg/compiler check.go)
                raise CompileError(
                    f"{cd.pos}: duplicate syscall {cd.name!r} "
                    f"(first declared at {seen_calls[cd.name]})")
            seen_calls[cd.name] = str(cd.pos)
            syscalls.append(Syscall(
                id=len(syscalls), nr=nr, name=cd.name,
                call_name=cd.call_name, args=args, ret=ret))

        target = Target(
            self.os, self.arch, ptr_size=self.ptr_size,
            page_size=self.page_size, syscalls=syscalls,
            resources=resources, consts=self.consts)
        return target

    # ------------------------------------------------------------------ #

    def _index_nodes(self) -> None:
        for n in self.desc.nodes:
            if isinstance(n, ResourceDef):
                self.resources[n.name] = n
            elif isinstance(n, StructDef):
                self.structs[n.name] = n
            elif isinstance(n, FlagsDef):
                self.flags[n.name] = n
            elif isinstance(n, StrFlagsDef):
                self.strflags[n.name] = n
            elif isinstance(n, CallDef):
                self.calls.append(n)

    def _resolve_defines(self) -> None:
        pending = [n for n in self.desc.nodes if isinstance(n, DefineDef)]
        for _ in range(len(pending) + 1):
            remaining = []
            for d in pending:
                try:
                    self.consts[d.name] = int(
                        eval(d.expr, {"__builtins__": {}}, self.consts))
                except Exception:
                    remaining.append(d)
            if not remaining:
                return
            if len(remaining) == len(pending):
                for d in remaining:
                    self.warnings.append(f"{d.pos}: cannot resolve define {d.name}")
                return
            pending = remaining

    def _const(self, e: Union[IntLit, Ident, TypeExpr], where: str) -> int:
        if isinstance(e, IntLit):
            return e.value
        name = e.name
        if name in self.consts:
            return self.consts[name]
        raise _SkipCall(f"unknown const {name!r} in {where}")

    # ------------------------------------------------------------------ #

    def _resource_desc(self, name: str) -> ResourceDesc:
        if name in self._res_desc_memo:
            return self._res_desc_memo[name]
        rd = self.resources.get(name)
        if rd is None:
            raise CompileError(f"unknown resource {name!r}")
        base_name = rd.base.name
        if base_name in self.resources:
            parent = self._resource_desc(base_name)
            kind = parent.kind + (name,)
            base_typ = parent.typ
            inherited = parent.values
        elif base_name in _INT_SIZES or base_name == "intptr":
            kind = (name,)
            base_typ = self._int_type(rd.base, Dir.IN, name)
            inherited = ()
        else:
            raise CompileError(
                f"{rd.pos}: resource {name} has bad base {base_name}")
        values: List[int] = []
        for v in rd.values:
            try:
                values.append(self._const(v, f"resource {name}"))
            except _SkipCall:
                self.warnings.append(f"{rd.pos}: dropping value in resource {name}")
        if not values:
            values = list(inherited) or [0]
        desc = ResourceDesc(name=name, typ=base_typ, kind=kind,
                            values=tuple(values))
        self._res_desc_memo[name] = desc
        return desc

    # ------------------------------------------------------------------ #

    def _int_type(self, te: TypeExpr, dir: Dir, fname: str) -> IntType:
        size = self.ptr_size if te.name == "intptr" else _INT_SIZES[te.name]
        big = te.name.endswith("be")
        kind, rb, re_ = IntKind.PLAIN, 0, 0
        args = _strip_opt(te.args)[0]
        if args:
            a = args[0]
            if isinstance(a, IntRange):
                kind = IntKind.RANGE
                rb = self._const(a.begin, fname)
                re_ = self._const(a.end, fname)
            else:
                kind = IntKind.RANGE
                rb = re_ = self._const(a, fname)
        bf = 0
        if te.bitfield_len is not None:
            bf = self._const(te.bitfield_len, fname)
            if bf > size * 8:
                raise CompileError(f"{te.pos}: bitfield of {bf} bits in {te.name}")
        return IntType(name=te.name, field_name=fname, size=size, dir=dir,
                       big_endian=big, kind=kind, range_begin=rb, range_end=re_,
                       bitfield_len=bf)

    def _base_type(self, args: list, dir: Dir, fname: str, *,
                   default_size: Optional[int] = None) -> IntType:
        """Last arg may be an int base type; default intptr."""
        for a in reversed(args):
            if isinstance(a, TypeExpr) and (a.name in _INT_SIZES or
                                            a.name == "intptr"):
                return self._int_type(a, dir, fname)
        size = default_size if default_size is not None else self.ptr_size
        return IntType(name="intptr", field_name=fname, size=size, dir=dir)

    def _make_type(self, te: TypeExpr, dir: Dir, fname: str,
                   is_arg: bool = False) -> Type:
        args, opt = _strip_opt(te.args)
        name = te.name

        if name in _INT_SIZES or name == "intptr":
            t = self._int_type(te, dir, fname)
            return replace(t, optional=opt)

        if name == "const":
            if not args:
                raise CompileError(f"{te.pos}: const needs a value")
            val = self._const(args[0], fname)
            base = self._base_type(args[1:], dir, fname)
            return ConstType(name="const", field_name=fname, size=base.size,
                             dir=dir, optional=opt, big_endian=base.big_endian,
                             val=val,
                             bitfield_len=self._bf(te, fname))

        if name == "flags":
            if not args or not isinstance(args[0], TypeExpr):
                raise CompileError(f"{te.pos}: flags needs a flag-set name")
            fl = self.flags.get(args[0].name)
            if fl is None:
                raise _SkipCall(f"unknown flags {args[0].name!r}")
            vals = []
            for v in fl.values:
                try:
                    vals.append(self._const(v, f"flags {fl.name}"))
                except _SkipCall:
                    self.warnings.append(
                        f"{fl.pos}: dropping unknown const in flags {fl.name}")
            base = self._base_type(args[1:], dir, fname)
            if not vals:
                return replace(base, field_name=fname, optional=opt)
            return FlagsType(name=fl.name, field_name=fname, size=base.size,
                             dir=dir, optional=opt, big_endian=base.big_endian,
                             vals=tuple(vals), bitfield_len=self._bf(te, fname))

        if name in ("len", "bytesize", "bytesize2", "bytesize4", "bytesize8"):
            if not args or not isinstance(args[0], TypeExpr):
                raise CompileError(f"{te.pos}: {name} needs a target field")
            byte_size = {"len": 0, "bytesize": 1, "bytesize2": 2,
                         "bytesize4": 4, "bytesize8": 8}[name]
            base = self._base_type(args[1:], dir, fname)
            return LenType(name=name, field_name=fname, size=base.size, dir=dir,
                           optional=opt, big_endian=base.big_endian,
                           buf=args[0].name, byte_size=byte_size,
                           bitfield_len=self._bf(te, fname))

        if name == "proc":
            if len(args) < 2:
                raise CompileError(f"{te.pos}: proc[start, perproc, base?]")
            start = self._const(args[0], fname)
            per = self._const(args[1], fname)
            base = self._base_type(args[2:], dir, fname)
            return ProcType(name="proc", field_name=fname, size=base.size,
                            dir=dir, optional=opt, big_endian=base.big_endian,
                            values_start=start, values_per_proc=per)

        if name == "csum":
            if len(args) < 2 or not isinstance(args[0], TypeExpr) \
                    or not isinstance(args[1], TypeExpr):
                raise CompileError(f"{te.pos}: csum[buf, kind, ...]")
            kind_name = args[1].name
            protocol = 0
            rest = args[2:]
            if kind_name == "inet":
                kind = CsumKind.INET
            elif kind_name == "pseudo":
                kind = CsumKind.PSEUDO
                if rest:
                    protocol = self._const(rest[0], fname)
                    rest = rest[1:]
            else:
                raise CompileError(f"{te.pos}: bad csum kind {kind_name}")
            base = self._base_type(rest, dir, fname)
            if not base.big_endian:
                # The executor stores checksums big-endian and the wire
                # format carries no endianness; network checksums are
                # network-order by definition, so require intNbe.
                raise CompileError(
                    f"{te.pos}: csum base type must be big-endian (int16be)")
            if base.size != 2:
                # The executor writes the 16-bit checksum at bytes 0-1 of
                # the field; a wider field would hold it in the wrong
                # (most-significant) bytes, silently shifting the value.
                raise CompileError(
                    f"{te.pos}: csum base type must be 2 bytes (int16be)")
            return CsumType(name="csum", field_name=fname, size=base.size,
                            dir=dir, big_endian=base.big_endian, kind=kind,
                            buf=args[0].name, protocol=protocol)

        if name == "fileoff":
            base = self._base_type(args, dir, fname)
            return replace(base, name="fileoff", kind=IntKind.FILEOFF,
                           field_name=fname, optional=opt)

        if name in ("vma", "vma64"):
            rb = re_ = 0
            if args:
                a = args[0]
                if isinstance(a, IntRange):
                    rb = self._const(a.begin, fname)
                    re_ = self._const(a.end, fname)
                else:
                    rb = re_ = self._const(a, fname)
            # vma64 is 8 bytes on every arch (reference prog/types.go VmaType).
            size = 8 if name == "vma64" else self.ptr_size
            return VmaType(name=name, field_name=fname, size=size,
                           dir=dir, optional=opt, range_begin=rb, range_end=re_)

        if name == "ptr":
            if len(args) < 2 or not isinstance(args[0], TypeExpr):
                raise CompileError(f"{te.pos}: ptr[dir, type]")
            pdir = _DIRS.get(args[0].name)
            if pdir is None:
                raise CompileError(f"{te.pos}: bad ptr direction {args[0].name}")
            elem = self._make_type(args[1], pdir, fname)
            return PtrType(name="ptr", field_name=fname, size=self.ptr_size,
                           dir=dir, optional=opt, elem=elem)

        if name == "buffer":
            if not args or not isinstance(args[0], TypeExpr):
                raise CompileError(f"{te.pos}: buffer[dir]")
            pdir = _DIRS.get(args[0].name)
            if pdir is None:
                raise CompileError(f"{te.pos}: bad buffer direction {args[0].name}")
            blob = BufferType(name="buffer", field_name=fname, size=0, dir=pdir,
                              kind=BufferKind.BLOB_RAND)
            return PtrType(name="ptr", field_name=fname, size=self.ptr_size,
                           dir=dir, optional=opt, elem=blob)

        if name in ("string", "stringnoz"):
            noz = name == "stringnoz"
            values: Tuple[str, ...] = ()
            sub_kind = ""
            fixed = 0
            for a in args:
                if isinstance(a, StrLit):
                    values = values + (a.value,)
                elif isinstance(a, TypeExpr) and a.name in self.strflags:
                    sub_kind = a.name
                    values = values + tuple(self.strflags[a.name].values)
                elif isinstance(a, (IntLit, Ident)):
                    fixed = self._const(a, fname)
                else:
                    raise CompileError(f"{te.pos}: bad string arg")
            bvals = tuple(v + ("" if noz else "\x00") for v in values)
            size = fixed
            if not size and bvals:
                sizes = {len(v) for v in bvals}
                if len(sizes) == 1:
                    size = sizes.pop()
            return BufferType(name=name, field_name=fname, size=size, dir=dir,
                              optional=opt, kind=BufferKind.STRING,
                              sub_kind=sub_kind, values=bvals)

        if name == "filename":
            return BufferType(name="filename", field_name=fname, size=0,
                              dir=dir, optional=opt, kind=BufferKind.FILENAME)

        if name == "text":
            if not args or not isinstance(args[0], TypeExpr) \
                    or args[0].name not in _TEXT_KINDS:
                raise CompileError(f"{te.pos}: text[kind]")
            return BufferType(name="text", field_name=fname, size=0, dir=dir,
                              kind=BufferKind.TEXT, text=_TEXT_KINDS[args[0].name])

        if name == "array":
            if not args or not isinstance(args[0], TypeExpr):
                raise CompileError(f"{te.pos}: array[type, len?]")
            elem = self._make_type(args[0], dir, fname)
            kind, rb, re_ = ArrayKind.RAND_LEN, 0, 0
            if len(args) > 1:
                a = args[1]
                kind = ArrayKind.RANGE_LEN
                if isinstance(a, IntRange):
                    rb = self._const(a.begin, fname)
                    re_ = self._const(a.end, fname)
                else:
                    rb = re_ = self._const(a, fname)
            size = 0
            if kind == ArrayKind.RANGE_LEN and rb == re_ and not elem.is_varlen:
                size = rb * elem.size
            # special case: array[int8] buffers degrade to blobs (byte arenas)
            if isinstance(elem, IntType) and elem.size == 1 \
                    and elem.kind == IntKind.PLAIN:
                bkind = BufferKind.BLOB_RAND
                if kind == ArrayKind.RANGE_LEN:
                    bkind = BufferKind.BLOB_RANGE
                return BufferType(name="array", field_name=fname, size=size,
                                  dir=dir, optional=opt, kind=bkind,
                                  range_begin=rb, range_end=re_)
            return ArrayType(name="array", field_name=fname, size=size, dir=dir,
                             optional=opt, elem=elem, kind=kind,
                             range_begin=rb, range_end=re_)

        if name in self.resources:
            desc = self._resource_desc(name)
            return ResourceType(name=name, field_name=fname,
                                size=desc.typ.size, dir=dir, optional=opt,
                                desc=desc)

        if name in self.structs:
            return self._struct_type(name, dir, fname, opt)

        if name == "bool8":
            return IntType(name="bool8", field_name=fname, size=1, dir=dir,
                           kind=IntKind.RANGE, range_begin=0, range_end=1)

        raise CompileError(f"{te.pos}: unknown type {name!r}")

    def _bf(self, te: TypeExpr, fname: str) -> int:
        return self._const(te.bitfield_len, fname) if te.bitfield_len else 0

    # ------------------------------------------------------------------ #

    def _struct_type(self, name: str, dir: Dir, fname: str, opt: bool) -> Type:
        key = (name, dir)
        if key in self._struct_memo:
            copy = replace(self._struct_memo[key], field_name=fname,
                           optional=opt)
            if key in self._struct_pending:
                # Recursive reference while the struct is still being built:
                # its fields/size aren't known yet, so register this copy to
                # be patched once the definition completes.
                self._struct_pending[key].append(copy)
            return copy
        sd = self.structs[name]
        if sd.is_union:
            shell = UnionType(name=name, field_name=fname, size=0, dir=dir)
        else:
            shell = StructType(name=name, field_name=fname, size=0, dir=dir)
        self._struct_memo[key] = shell
        self._struct_pending[key] = []

        fields = tuple(self._make_type(f.typ, dir, f.name) for f in sd.fields)
        patch: Dict[str, object] = {}
        if sd.is_union:
            varlen = any(f.is_varlen for f in fields) or \
                len({f.size for f in fields}) > 1
            patch = {"fields": fields, "size": 0 if varlen else fields[0].size}
        else:
            packed = "packed" in sd.attrs
            align_attr = 0
            for a in sd.attrs:
                if a.startswith("align_"):
                    align_attr = int(a[len("align_"):], 0)
            fields, size, varlen = self._layout_struct(fields, packed, align_attr)
            patch = {"fields": fields, "size": 0 if varlen else size,
                     "align_attr": align_attr, "packed": packed}
        for inst in [shell] + self._struct_pending.pop(key):
            for k, v in patch.items():
                object.__setattr__(inst, k, v)
        return replace(shell, field_name=fname, optional=opt)

    def _layout_struct(self, fields: Tuple[Type, ...], packed: bool,
                       align_attr: int):
        """Insert alignment padding and assign bitfield offsets.

        Returns (fields_with_pads, static_size, varlen)."""
        out: List[Type] = []
        offset = 0
        varlen = False
        max_align = 1
        i = 0
        fields = list(fields)
        while i < len(fields):
            f = fields[i]
            # bitfield group: consecutive int-like fields with bitfield_len
            if getattr(f, "bitfield_len", 0):
                unit = f.size
                bits = 0
                group = []
                while i < len(fields):
                    g = fields[i]
                    gl = getattr(g, "bitfield_len", 0)
                    if not gl or g.size != unit or bits + gl > unit * 8:
                        break
                    group.append((g, bits))
                    bits += gl
                    i += 1
                for j, (g, off_bits) in enumerate(group):
                    middle = j != len(group) - 1
                    out.append(replace(g, bitfield_off=off_bits,
                                       bitfield_mdl=middle))
                offset += unit
                max_align = max(max_align, unit)
                continue
            al = 1 if packed else self._type_align(f)
            max_align = max(max_align, al)
            if not varlen and al > 1 and offset % al:
                pad = al - offset % al
                out.append(ConstType(name="pad", field_name=f"_pad{offset}",
                                     size=pad, dir=f.dir, is_pad=True))
                offset += pad
            out.append(f)
            if f.is_varlen:
                varlen = True
            else:
                offset += f.size
            i += 1
        struct_align = align_attr or (1 if packed else max_align)
        if not varlen and struct_align > 1 and offset % struct_align:
            pad = struct_align - offset % struct_align
            out.append(ConstType(name="pad", field_name=f"_pad{offset}",
                                 size=pad, dir=Dir.IN, is_pad=True))
            offset += pad
        return tuple(out), offset, varlen

    def _type_align(self, t: Type) -> int:
        if isinstance(t, (PtrType, VmaType)):
            return self.ptr_size
        if isinstance(t, BufferType):
            return 1
        if isinstance(t, ArrayType):
            return self._type_align(t.elem)
        if isinstance(t, StructType):
            if t.align_attr:
                return t.align_attr
            if t.packed:
                return 1
            return max((self._type_align(f) for f in t.fields), default=1)
        if isinstance(t, UnionType):
            return max((self._type_align(f) for f in t.fields), default=1)
        if isinstance(t, ResourceType):
            return t.desc.typ.size
        sz = t.size
        return sz if sz in (1, 2, 4, 8) else 8


class _SkipCall(Exception):
    """A call references something unresolvable; it is recorded as
    unsupported rather than failing the whole compile (matches the
    reference's disabled-syscall behavior)."""


def _strip_opt(args: list) -> Tuple[list, bool]:
    opt = False
    out = []
    for a in args:
        if isinstance(a, TypeExpr) and a.name == "opt" and not a.args:
            opt = True
        else:
            out.append(a)
    return out, opt


def compile_description(desc: Description, consts: Dict[str, int], **kw) -> Target:
    return Compiler(desc, consts, **kw).compile()
