"""Bundled freebsd/amd64 target: descriptions + consts + arch hooks.

Plays the role of the reference's sys/freebsd target (generated
sys/freebsd/amd64.go + hand-written init.go; reference:
/root/reference/sys/freebsd/init.go:10-60): compiles the bundled
description files at first use and registers a Target with the
mmap hooks wired in.  FreeBSD's mmap call shape matches linux's
six-argument form, so make_mmap/analyze_mmap mirror the linux hooks
with FreeBSD flag values from consts_amd64.json.
"""

from __future__ import annotations

from pathlib import Path

from ...prog import prog as progmod
from ...prog.target import Target
from ..bundle import build_bundled_target, ensure_bundled_registered

_HERE = Path(__file__).parent

STRING_DICTIONARY = [
    "user", "wheel", "operator", "devfs", "procfs", "tmpfs", "nullfs",
    "lo0", "em0", "tun0", "jail",
]

# Signals that can't take down the executor process group: 0 (existence
# test), SIGCHLD, SIGWINCH, SIGUSR1/2 are either ignored by default or
# handled by the executor.  Everything else is rewritten by sanitize_call
# (the linux corpus restricts kill the same way, linux/signal.txt).
SAFE_SIGNALS = (0, 20, 28, 30, 31)


def build_target(arch: str = "amd64") -> Target:
    return build_bundled_target("freebsd", arch, _HERE, init_arch=_init_arch)


def _init_arch(target: Target) -> None:
    mmap = target.syscall_map.get("mmap")
    cm = target.consts
    prot_rw = cm["PROT_READ"] | cm["PROT_WRITE"]
    map_flags = cm["MAP_ANONYMOUS"] | cm["MAP_PRIVATE"] | cm["MAP_FIXED"]
    invalid_fd = (1 << 64) - 1

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=mmap,
            args=[
                progmod.PointerArg(mmap.args[0], start, 0, npages, None),
                progmod.ConstArg(mmap.args[1], npages * target.page_size),
                progmod.ConstArg(mmap.args[2], prot_rw),
                progmod.ConstArg(mmap.args[3], map_flags),
                progmod.ConstArg(mmap.args[4], invalid_fd),
                progmod.ConstArg(mmap.args[5], 0),
            ],
            ret=progmod.ReturnArg(mmap.ret) if mmap.ret else progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        name = c.meta.name
        if name == "mmap":
            npages = c.args[1].val // target.page_size
            return c.args[0].page_index, npages, npages > 0
        if name == "munmap":
            return c.args[0].page_index, c.args[1].val // target.page_size, False
        return 0, 0, False

    def sanitize_call(c: progmod.Call) -> None:
        cn = c.meta.call_name
        if cn == "mmap":
            c.args[3].val |= cm["MAP_FIXED"]
        elif cn == "kill" and len(c.args) >= 2:
            if c.args[1].val not in SAFE_SIGNALS:
                c.args[1].val = 0

    if mmap is not None:
        target.mmap_syscall = mmap
        target.make_mmap = make_mmap
        target.analyze_mmap = analyze_mmap
    target.sanitize_call = sanitize_call
    target.string_dictionary = list(STRING_DICTIONARY)


def ensure_registered(arch: str = "amd64") -> Target:
    return ensure_bundled_registered("freebsd", arch, build_target)
