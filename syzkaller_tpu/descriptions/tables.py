"""Compile a Target into flat numpy tables for the device kernels.

This is the TPU-first replacement for the reference's generated-Go type graph
(reference: sys/syz-sysgen emitting sys/linux/<arch>.go): instead of walking
typed trees at runtime, every syscall is flattened once into a *static slot
template* — the exact sequence of exec-format atoms (register args + copyin
fields) it produces — plus value-sampling metadata per slot. The batched
JAX generation/mutation kernels then operate purely on integer tensors
indexed by these tables.

Design notes:
  - Each call's pointee memory is modeled as one contiguous per-call byte
    arena (the copyin image); pointer targets ("blocks") are static offsets
    into it. Programs then need no page allocator on device: the encoder
    prepends a single uber-mmap covering the arena (the same normalization
    the reference's minimizer applies, prog/mutation.go:274-310).
  - Variable-length constructs are instantiated at their minimum legal
    shape (arrays at range_begin/1 element, unions at option 0); the host
    mutator covers the long tail, per SURVEY.md §7 phase 2.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..prog.prio import calc_static_priorities
from ..prog.types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    UnionType,
    VmaType,
    is_pad,
)

# type-table kinds
TK_INT = 0
TK_FLAGS = 1
TK_CONST = 2
TK_LEN = 3
TK_PROC = 4
TK_CSUM = 5
TK_RES = 6
TK_BUF_BLOB = 7
TK_BUF_STR = 8
TK_BUF_FILE = 9
TK_BUF_TEXT = 10
TK_PTR = 11
TK_VMA = 12

# slot kinds
SK_VALUE = 0   # scalar written as-is (register arg or copyin field)
SK_REF = 1     # resource: references the ret of an earlier call (or default)
SK_PTR = 2     # pointer to a block in the call arena
SK_DATA = 3    # byte payload inside the call arena
SK_VMA = 4     # address of N pages in the vma region
SK_LEN = 5     # length of a sibling slot / enclosing block (recomputed)

DEFAULT_BLOB_CAP = 64
MAX_DATA_CAP = 512
MAX_CALL_ARENA = 2048
MAX_SLOTS_PER_CALL = 48


@dataclass
class _Slot:
    type_idx: int
    kind: int
    is_arg: bool
    arg_idx: int          # top-level arg position, or -1
    block: int            # block id the slot's bytes live in (-1: register)
    offset: int           # byte offset within the block
    size: int             # byte width of the value (or data cap for SK_DATA)
    res_kind: int = -1    # for SK_REF
    len_target: int = -1  # for SK_LEN: slot index within this call
    len_block: int = -1   # for SK_LEN buf == "parent": block id
    default: int = 0
    group: int = 0        # sibling scope for len resolution
    fname: str = ""
    target_block: int = -1  # for SK_PTR: the pointed-to block
    str_off: int = -1     # for SK_DATA strings: offset into string pool
    str_cnt: int = 0


@dataclass
class CompiledTables:
    target: object
    n_calls: int
    n_res_kinds: int
    res_kind_names: List[str]

    # type table (indexed by slot type_idx)
    type_kind: np.ndarray
    type_size: np.ndarray
    type_lo: np.ndarray        # int range lo / proc start / blob min len
    type_hi: np.ndarray        # int range hi / proc per-proc / blob max len
    type_flags_off: np.ndarray
    type_flags_cnt: np.ndarray
    type_default: np.ndarray
    type_bf_off: np.ndarray
    type_bf_len: np.ndarray
    type_big_endian: np.ndarray
    flags_pool: np.ndarray     # u64 values

    # per-syscall
    call_nargs: np.ndarray
    call_slot_off: np.ndarray
    call_slot_cnt: np.ndarray
    call_arena_size: np.ndarray
    call_vma_pages: np.ndarray     # pages consumed by vma slots
    call_ret_kind: np.ndarray      # resource kind produced by ret (-1 none)
    call_res_out: np.ndarray       # [n_calls, n_res_kinds] u8 produces-matrix
    call_res_in: np.ndarray        # [n_calls, n_res_kinds] u8 needs-matrix

    # flattened slot templates
    slot_type: np.ndarray
    slot_kind: np.ndarray
    slot_is_arg: np.ndarray
    slot_arg_idx: np.ndarray
    slot_block: np.ndarray
    slot_offset: np.ndarray
    slot_size: np.ndarray
    slot_res_kind: np.ndarray
    slot_len_target: np.ndarray
    slot_len_block: np.ndarray
    slot_default: np.ndarray
    slot_target_block: np.ndarray
    slot_str_off: np.ndarray
    slot_str_cnt: np.ndarray

    # per-call block layout
    call_block_off: np.ndarray     # into block_size/block_addr
    call_block_cnt: np.ndarray
    block_size: np.ndarray
    block_addr: np.ndarray         # static offset within the call arena

    # string pool
    str_data: np.ndarray           # [n_strings, MAX_DATA_CAP] u8
    str_len: np.ndarray

    # resource machinery
    res_compat: np.ndarray         # [R, R] u8: can src kind satisfy dst kind
    ctor_of_kind: np.ndarray       # [R] preferred ctor syscall id (-1 none)

    # priorities
    prio_static: np.ndarray        # [n_calls, n_calls] f32

    # bookkeeping for decode
    max_slots: int = 0
    max_arena: int = 0

    def call_name(self, call_id: int) -> str:
        return self.target.syscalls[call_id].name


class _TypeTable:
    def __init__(self):
        self.rows: List[tuple] = []
        self.memo: Dict[tuple, int] = {}
        self.flags_pool: List[int] = []
        self.str_data: List[bytes] = []

    def add_flags(self, vals: Tuple[int, ...]) -> Tuple[int, int]:
        off = len(self.flags_pool)
        self.flags_pool.extend(vals)
        return off, len(vals)

    def add_strings(self, vals: Tuple[str, ...]) -> Tuple[int, int]:
        off = len(self.str_data)
        for v in vals:
            self.str_data.append(v.encode("latin1")[:MAX_DATA_CAP])
        return off, len(vals)

    def intern(self, key: tuple, row: tuple) -> int:
        if key in self.memo:
            return self.memo[key]
        idx = len(self.rows)
        self.rows.append(row)
        self.memo[key] = idx
        return idx


def _res_kind_index(target) -> Dict[str, int]:
    return {r.name: i for i, r in enumerate(target.resources)}


def compile_tables(target) -> CompiledTables:
    res_idx = _res_kind_index(target)
    nres = len(res_idx)
    tt = _TypeTable()

    U64 = (1 << 64) - 1

    def type_row(t, tk: int, lo=0, hi=0, foff=0, fcnt=0, default=0,
                 soff=-1, scnt=0) -> int:
        key = (tk, t.size, lo & U64, hi & U64, foff, fcnt, default & U64,
               t.bitfield_offset, t.bitfield_length,
               getattr(t, "big_endian", False), soff, scnt)
        return tt.intern(key, key)

    all_slots: List[_Slot] = []
    call_slot_off: List[int] = []
    call_slot_cnt: List[int] = []
    call_arena: List[int] = []
    call_vma_pages: List[int] = []
    call_nargs: List[int] = []
    call_ret_kind: List[int] = []
    call_res_out = np.zeros((len(target.syscalls), nres), dtype=np.uint8)
    call_res_in = np.zeros((len(target.syscalls), nres), dtype=np.uint8)
    call_block_off: List[int] = []
    call_block_cnt: List[int] = []
    block_sizes: List[int] = []
    block_addrs: List[int] = []

    for ci, meta in enumerate(target.syscalls):
        slots: List[_Slot] = []
        blocks: List[int] = []  # sizes
        vma_pages = [0]
        group_counter = [0]

        def new_block(size: int) -> int:
            bid = len(blocks)
            blocks.append(min(size, MAX_CALL_ARENA))
            return bid

        def flatten(t, is_arg: bool, arg_idx: int, block: int, offset: int,
                    group: int) -> int:
            """Append slots for type t; returns its byte size in the block."""
            if len(slots) >= MAX_SLOTS_PER_CALL:
                return 0 if t.is_varlen else t.size
            if isinstance(t, ResourceType):
                rk = res_idx[t.desc.name]
                if t.dir != Dir.IN:
                    call_res_out[ci, rk] = 1
                    # kernel writes it; device models as value slot
                    ti = type_row(t, TK_RES, default=t.default())
                    slots.append(_Slot(ti, SK_VALUE, is_arg, arg_idx, block,
                                       offset, t.size, res_kind=rk,
                                       default=t.default(), group=group,
                                       fname=t.field_name))
                else:
                    call_res_in[ci, rk] = 1
                    ti = type_row(t, TK_RES, default=t.default())
                    slots.append(_Slot(ti, SK_REF, is_arg, arg_idx, block,
                                       offset, t.size, res_kind=rk,
                                       default=t.default(), group=group,
                                       fname=t.field_name))
                return t.size
            if isinstance(t, (IntType,)):
                lo, hi = (t.range_begin, t.range_end) \
                    if t.kind == IntKind.RANGE else (0, 0)
                ti = type_row(t, TK_INT, lo=lo, hi=hi)
                slots.append(_Slot(ti, SK_VALUE, is_arg, arg_idx, block,
                                   offset, t.size, group=group,
                                   fname=t.field_name))
                return t.size if not t.bitfield_middle else 0
            if isinstance(t, FlagsType):
                foff, fcnt = tt.add_flags(t.vals)
                ti = type_row(t, TK_FLAGS, foff=foff, fcnt=fcnt)
                slots.append(_Slot(ti, SK_VALUE, is_arg, arg_idx, block,
                                   offset, t.size, group=group,
                                   fname=t.field_name))
                return t.size if not t.bitfield_middle else 0
            if isinstance(t, ConstType):
                if is_pad(t):
                    return t.size
                ti = type_row(t, TK_CONST, default=t.val)
                slots.append(_Slot(ti, SK_VALUE, is_arg, arg_idx, block,
                                   offset, t.size, default=t.val, group=group,
                                   fname=t.field_name))
                return t.size if not t.bitfield_middle else 0
            if isinstance(t, ProcType):
                ti = type_row(t, TK_PROC, lo=t.values_start,
                              hi=t.values_per_proc)
                slots.append(_Slot(ti, SK_VALUE, is_arg, arg_idx, block,
                                   offset, t.size, group=group,
                                   fname=t.field_name))
                return t.size if not t.bitfield_middle else 0
            if isinstance(t, CsumType):
                # SK_LEN: recomputed (by the executor at run time), never
                # mutated — a device-proposed value would poison the inet
                # sum, whose buf range includes this field as zero.
                ti = type_row(t, TK_CSUM)
                slots.append(_Slot(ti, SK_LEN, is_arg, arg_idx, block,
                                   offset, t.size, group=group,
                                   fname=t.field_name))
                return t.size
            if isinstance(t, LenType):
                ti = type_row(t, TK_LEN, lo=t.byte_size)
                slots.append(_Slot(ti, SK_LEN, is_arg, arg_idx, block,
                                   offset, t.size, group=group,
                                   fname=t.field_name))
                # len target resolved after the call is flattened
                slots[-1].len_target = -1
                slots[-1].fname = t.field_name
                slots[-1].str_off = -1
                slots[-1].len_block = -1
                slots[-1].__dict__["len_buf"] = t.buf
                return t.size if not t.bitfield_middle else 0
            if isinstance(t, VmaType):
                npages = max(1, t.range_begin)
                ti = type_row(t, TK_VMA, lo=t.range_begin, hi=t.range_end)
                slots.append(_Slot(ti, SK_VMA, is_arg, arg_idx, block,
                                   offset, t.size, default=npages,
                                   group=group, fname=t.field_name))
                vma_pages[0] += npages
                return t.size
            if isinstance(t, BufferType):
                if t.kind == BufferKind.STRING:
                    soff, scnt = tt.add_strings(t.values)
                    cap = t.size or max(
                        [len(v) for v in t.values] + [DEFAULT_BLOB_CAP])
                    cap = min(cap, MAX_DATA_CAP)
                    ti = type_row(t, TK_BUF_STR, soff=soff, scnt=scnt)
                    sl = _Slot(ti, SK_DATA, is_arg, arg_idx, block, offset,
                               cap, group=group, fname=t.field_name,
                               str_off=soff, str_cnt=scnt)
                    slots.append(sl)
                    return cap
                if t.kind == BufferKind.FILENAME:
                    ti = type_row(t, TK_BUF_FILE)
                    cap = min(t.size or 16, MAX_DATA_CAP)
                    slots.append(_Slot(ti, SK_DATA, is_arg, arg_idx, block,
                                       offset, cap, group=group,
                                       fname=t.field_name))
                    return cap
                tk = TK_BUF_TEXT if t.kind == BufferKind.TEXT else TK_BUF_BLOB
                lo = t.range_begin
                hi = t.range_end if t.kind == BufferKind.BLOB_RANGE \
                    else DEFAULT_BLOB_CAP
                cap = min(t.size or max(hi, 1), MAX_DATA_CAP)
                ti = type_row(t, tk, lo=lo, hi=min(hi, cap))
                slots.append(_Slot(ti, SK_DATA, is_arg, arg_idx, block,
                                   offset, cap, group=group,
                                   fname=t.field_name))
                return cap
            if isinstance(t, PtrType):
                elem = t.elem
                esize = elem.size if not elem.is_varlen else 0
                bid = new_block(max(esize, 8))
                ti = type_row(t, TK_PTR)
                sl = _Slot(ti, SK_PTR, is_arg, arg_idx, block, offset, t.size,
                           group=group, fname=t.field_name, target_block=bid)
                slots.append(sl)
                g = group_counter[0] = group_counter[0] + 1
                inner = flatten(elem, False, -1, bid, 0, g)
                blocks[bid] = min(max(blocks[bid], inner, 1), MAX_CALL_ARENA)
                return t.size
            if isinstance(t, StructType):
                off = 0
                g = group_counter[0] = group_counter[0] + 1
                for f in t.fields:
                    sz = flatten(f, False, -1, block, offset + off, g)
                    if is_pad(f):
                        off += f.size
                    elif not f.bitfield_middle:
                        off += sz if f.is_varlen or not isinstance(
                            f, BufferType) else sz
                return off if t.is_varlen else max(t.size, off)
            if isinstance(t, UnionType):
                g = group_counter[0] = group_counter[0] + 1
                inner = flatten(t.fields[0], False, -1, block, offset, g)
                return t.size if not t.is_varlen else inner
            if isinstance(t, ArrayType):
                if t.kind == ArrayKind.RANGE_LEN:
                    count = max(t.range_begin, 1)
                else:
                    count = 1
                off = 0
                g = group_counter[0] = group_counter[0] + 1
                for _ in range(count):
                    off += flatten(t.elem, False, -1, block, offset + off, g)
                    if len(slots) >= MAX_SLOTS_PER_CALL:
                        break
                return off
            raise TypeError(f"cannot flatten {t}")

        for i, at in enumerate(meta.args):
            flatten(at, True, i, -1, 0, 0)

        # resolve len targets: sibling field in the same group, else
        # the enclosing block ("parent")
        for si, sl in enumerate(slots):
            if sl.kind != SK_LEN:
                continue
            buf = sl.__dict__.get("len_buf", "")
            target_si = -1
            for sj, other in enumerate(slots):
                if sj != si and other.group == sl.group \
                        and other.fname == buf:
                    target_si = sj
                    break
            if target_si >= 0:
                # a len of a pointer arg measures its pointee block
                if slots[target_si].kind == SK_PTR:
                    sl.len_block = slots[target_si].target_block
                    sl.len_target = -1
                else:
                    sl.len_target = target_si
            elif buf == "parent" and sl.block >= 0:
                sl.len_block = sl.block
            else:
                sl.len_target = -1  # stays at default 0

        # lay out blocks inside the call arena (8-byte aligned)
        addrs = []
        cur = 0
        for bs in blocks:
            addrs.append(cur)
            cur += (bs + 7) & ~7
        cur = min(cur, MAX_CALL_ARENA)

        call_slot_off.append(len(all_slots))
        call_slot_cnt.append(len(slots))
        call_arena.append(cur)
        call_vma_pages.append(vma_pages[0])
        call_nargs.append(len(meta.args))
        rk = -1
        if meta.ret is not None and isinstance(meta.ret, ResourceType):
            rk = res_idx[meta.ret.desc.name]
            call_res_out[ci, rk] = 1
        call_ret_kind.append(rk)
        call_block_off.append(len(block_sizes))
        call_block_cnt.append(len(blocks))
        block_sizes.extend(blocks)
        block_addrs.extend(addrs)
        all_slots.extend(slots)

    # resource compat matrix + preferred ctors
    res_compat = np.zeros((max(nres, 1), max(nres, 1)), dtype=np.uint8)
    for dname, di in res_idx.items():
        for sname, si in res_idx.items():
            if target.is_compatible_resource(dname, sname):
                res_compat[di, si] = 1
    ctor_of_kind = np.full(max(nres, 1), -1, dtype=np.int32)
    for rname, ri in res_idx.items():
        # prefer ctors that produce exactly this kind (socket for sock,
        # not any fd producer); fall back to imprecise
        ctors = target.calc_resource_ctors(
            target.resource_map[rname].kind, precise=True) \
            or target.resource_ctors.get(rname, [])
        if ctors:
            # cheapest ctor: fewest input resources, then fewest slots
            best = min(
                ctors,
                key=lambda m: (int(call_res_in[m.id].sum()),
                               call_slot_cnt[m.id]))
            ctor_of_kind[ri] = best.id

    # type table columns
    n_types = len(tt.rows)
    cols = list(zip(*tt.rows)) if n_types else [[]] * 12
    type_kind = np.array(cols[0], dtype=np.int32)
    type_size = np.array(cols[1], dtype=np.int32)
    type_lo = np.array(cols[2], dtype=np.uint64)
    type_hi = np.array(cols[3], dtype=np.uint64)
    type_flags_off = np.array(cols[4], dtype=np.int32)
    type_flags_cnt = np.array(cols[5], dtype=np.int32)
    type_default = np.array(cols[6], dtype=np.uint64)
    type_bf_off = np.array(cols[7], dtype=np.int32)
    type_bf_len = np.array(cols[8], dtype=np.int32)
    type_big_endian = np.array(cols[9], dtype=np.uint8)

    str_data = np.zeros((max(len(tt.str_data), 1), MAX_DATA_CAP),
                        dtype=np.uint8)
    str_len = np.zeros(max(len(tt.str_data), 1), dtype=np.int32)
    for i, b in enumerate(tt.str_data):
        str_data[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        str_len[i] = len(b)

    U64 = (1 << 64) - 1

    def col(attr, dtype=np.int32):
        vals = [getattr(s, attr) for s in all_slots] or [0]
        if dtype == np.uint64:
            vals = [v & U64 for v in vals]
        return np.array(vals, dtype=dtype)

    tables = CompiledTables(
        target=target,
        n_calls=len(target.syscalls),
        n_res_kinds=nres,
        res_kind_names=list(res_idx),
        type_kind=type_kind, type_size=type_size, type_lo=type_lo,
        type_hi=type_hi, type_flags_off=type_flags_off,
        type_flags_cnt=type_flags_cnt, type_default=type_default,
        type_bf_off=type_bf_off, type_bf_len=type_bf_len,
        type_big_endian=type_big_endian,
        flags_pool=np.array([v & ((1 << 64) - 1) for v in tt.flags_pool]
                            or [0], dtype=np.uint64),
        call_nargs=np.array(call_nargs, dtype=np.int32),
        call_slot_off=np.array(call_slot_off, dtype=np.int32),
        call_slot_cnt=np.array(call_slot_cnt, dtype=np.int32),
        call_arena_size=np.array(call_arena, dtype=np.int32),
        call_vma_pages=np.array(call_vma_pages, dtype=np.int32),
        call_ret_kind=np.array(call_ret_kind, dtype=np.int32),
        call_res_out=call_res_out,
        call_res_in=call_res_in,
        slot_type=col("type_idx"),
        slot_kind=col("kind"),
        slot_is_arg=col("is_arg", np.uint8),
        slot_arg_idx=col("arg_idx"),
        slot_block=col("block"),
        slot_offset=col("offset"),
        slot_size=col("size"),
        slot_res_kind=col("res_kind"),
        slot_len_target=col("len_target"),
        slot_len_block=col("len_block"),
        slot_default=col("default", np.uint64),
        slot_target_block=col("target_block"),
        slot_str_off=col("str_off"),
        slot_str_cnt=col("str_cnt"),
        call_block_off=np.array(call_block_off, dtype=np.int32),
        call_block_cnt=np.array(call_block_cnt, dtype=np.int32),
        block_size=np.array(block_sizes or [0], dtype=np.int32),
        block_addr=np.array(block_addrs or [0], dtype=np.int32),
        str_data=str_data,
        str_len=str_len,
        res_compat=res_compat,
        ctor_of_kind=ctor_of_kind,
        prio_static=calc_static_priorities(target),
        max_slots=int(max(call_slot_cnt)) if call_slot_cnt else 0,
        max_arena=int(max(call_arena)) if call_arena else 0,
    )
    return tables


_cache: Dict[int, CompiledTables] = {}


def get_tables(target) -> CompiledTables:
    key = id(target)
    if key not in _cache:
        _cache[key] = compile_tables(target)
    return _cache[key]
