"""Canonical formatter for syscall description files.

Re-serializes the parsed AST back to the description language's canonical
layout (reference /root/reference/pkg/ast/format.go: tab-separated struct
fields, `name(args) ret` calls, brace-wrapped struct/union bodies).
Formatting is idempotent: format(parse(format(parse(x)))) == format(parse(x)).
"""

from __future__ import annotations

from typing import List, Union

from . import ast


def _quote(s: str) -> str:
    """Inverse of parser._unescape (unicode_escape with quote handling)."""
    body = (s.encode("unicode_escape").decode("ascii")
            .replace('"', '\\"'))
    return f'"{body}"'


def _expr(e) -> str:
    if isinstance(e, ast.IntLit):
        v = e.value
        return hex(v) if v >= 10 else str(v)
    if isinstance(e, ast.Ident):
        return e.name
    if isinstance(e, ast.StrLit):
        return _quote(e.value)
    if isinstance(e, ast.IntRange):
        return f"{_expr(e.begin)}:{_expr(e.end)}"
    if isinstance(e, ast.TypeExpr):
        return _type(e)
    raise TypeError(f"unknown expr node {e!r}")


def _type(t: ast.TypeExpr) -> str:
    s = t.name
    if t.args:
        s += "[" + ", ".join(_expr(a) for a in t.args) + "]"
    if t.bitfield_len is not None:
        s += ":" + _expr(t.bitfield_len)
    return s


def _call(c: ast.CallDef) -> str:
    args = ", ".join(f"{f.name} {_type(f.typ)}" for f in c.fields)
    s = f"{c.name}({args})"
    if c.ret is not None:
        s += " " + _type(c.ret)
    return s


def _struct(s: ast.StructDef) -> List[str]:
    op, cl = ("[", "]") if s.is_union else ("{", "}")
    lines = [f"{s.name} {op}"]
    width = max((len(f.name) for f in s.fields), default=0)
    for f in s.fields:
        lines.append(f"\t{f.name.ljust(width)}\t{_type(f.typ)}")
    tail = cl
    if s.attrs:
        tail += " [" + ", ".join(s.attrs) + "]"
    lines.append(tail)
    return lines


def format_node(n: ast.Node) -> List[str]:
    if isinstance(n, ast.CallDef):
        return [_call(n)]
    if isinstance(n, ast.ResourceDef):
        s = f"resource {n.name}[{_type(n.base)}]"
        if n.values:
            s += ": " + ", ".join(_expr(v) for v in n.values)
        return [s]
    if isinstance(n, ast.FlagsDef):
        return [f"{n.name} = " + ", ".join(_expr(v) for v in n.values)]
    if isinstance(n, ast.StrFlagsDef):
        return [f"{n.name} = " + ", ".join(_quote(v) for v in n.values)]
    if isinstance(n, ast.StructDef):
        return _struct(n)
    if isinstance(n, ast.DefineDef):
        return [f"define {n.name} {n.expr}"]
    if isinstance(n, ast.IncludeDef):
        return [f"include <{n.path}>"]
    raise TypeError(f"unknown node {n!r}")


def format_description(desc: ast.Description) -> str:
    """Canonical text: one blank line between definition groups; struct
    and union bodies separated from scalar definitions."""
    out: List[str] = []
    prev_kind = None
    for n in desc.nodes:
        kind = type(n).__name__
        block = isinstance(n, ast.StructDef)
        if out and (block or kind != prev_kind):
            out.append("")
        out.extend(format_node(n))
        prev_kind = kind
    return "\n".join(out) + "\n"


def format_file(path: str, write: bool = False) -> Union[str, bool]:
    """Format one .txt description file. With write=True, rewrites the
    file in place and returns whether it changed."""
    from .parser import parse

    with open(path) as f:
        src = f.read()
    text = format_description(parse(src, path))
    parse(text, path)  # never overwrite with text that doesn't re-parse
    if not write:
        return text
    if text != src:
        with open(path, "w") as f:
            f.write(text)
        return True
    return False
