"""Recursive-descent parser for the syscall description language.

Source-compatible with the reference description syntax (reference:
/root/reference/pkg/ast/parser.go:17-50 and sys/linux/*.txt). Line-oriented:
every top-level construct starts on its own line; structs/unions span lines
until the closing brace/bracket.
"""

from __future__ import annotations

import re
from typing import List, Optional, Union

from .ast import (
    CallDef,
    DefineDef,
    Description,
    Field,
    FlagsDef,
    Ident,
    IncludeDef,
    IntLit,
    IntRange,
    Pos,
    ResourceDef,
    StrFlagsDef,
    StrLit,
    StructDef,
    TypeExpr,
)


class ParseError(Exception):
    def __init__(self, pos: Pos, msg: str):
        super().__init__(f"{pos}: {msg}")
        self.pos = pos


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\#.*)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<char>'(?:[^'\\]|\\.)')
  | (?P<number>-?(?:0x[0-9a-fA-F]+|\d+))
  | (?P<ident>[a-zA-Z_][a-zA-Z0-9_$]*)
  | (?P<punct>[()\[\]{}:,=<>])
""",
    re.VERBOSE,
)


class _Lexer:
    def __init__(self, text: str, pos: Pos):
        self.pos = pos
        self.toks: List[tuple] = []  # (kind, value)
        i = 0
        while i < len(text):
            m = _TOKEN_RE.match(text, i)
            if not m:
                raise ParseError(pos, f"bad character {text[i]!r}")
            i = m.end()
            kind = m.lastgroup
            if kind in ("ws", "comment"):
                continue
            val = m.group()
            if kind == "number":
                self.toks.append(("number", int(val, 0)))
            elif kind == "string":
                self.toks.append(("string", _unescape(val[1:-1])))
            elif kind == "char":
                self.toks.append(("number", ord(_unescape(val[1:-1]))))
            else:
                self.toks.append((kind, val))
        self.i = 0

    def peek(self) -> Optional[tuple]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> tuple:
        t = self.peek()
        if t is None:
            raise ParseError(self.pos, "unexpected end of line")
        self.i += 1
        return t

    def accept(self, kind: str, value=None) -> Optional[tuple]:
        t = self.peek()
        if t and t[0] == kind and (value is None or t[1] == value):
            self.i += 1
            return t
        return None

    def expect(self, kind: str, value=None) -> tuple:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            raise ParseError(
                self.pos,
                f"expected {value or kind}, got {got[1] if got else 'EOL'}")
        return t

    @property
    def eol(self) -> bool:
        return self.i >= len(self.toks)


def _unescape(s: str) -> str:
    return s.encode().decode("unicode_escape")


def _parse_expr(lx: _Lexer) -> Union[IntLit, Ident]:
    t = lx.next()
    if t[0] == "number":
        return IntLit(t[1], lx.pos)
    if t[0] == "ident":
        return Ident(t[1], lx.pos)
    raise ParseError(lx.pos, f"expected const expression, got {t[1]!r}")


def _parse_type(lx: _Lexer) -> TypeExpr:
    name = lx.expect("ident")[1]
    te = TypeExpr(name, pos=lx.pos)
    if lx.accept("punct", "["):
        while True:
            te.args.append(_parse_type_arg(lx))
            if lx.accept("punct", "]"):
                break
            lx.expect("punct", ",")
    if lx.accept("punct", ":"):
        te.bitfield_len = _parse_expr(lx)
    return te


def _parse_type_arg(lx: _Lexer):
    t = lx.peek()
    if t is None:
        raise ParseError(lx.pos, "unexpected end of type args")
    if t[0] == "string":
        lx.next()
        return StrLit(t[1], lx.pos)
    if t[0] == "number":
        lx.next()
        first: Union[IntLit, Ident] = IntLit(t[1], lx.pos)
    elif t[0] == "ident":
        # Could be an ident const, a nested type, or the start of a range.
        te = _parse_type(lx)
        if te.args or te.bitfield_len is not None:
            return te
        first = Ident(te.name, te.pos)
    else:
        raise ParseError(lx.pos, f"bad type argument {t[1]!r}")
    if lx.accept("punct", ":"):
        second = _parse_expr(lx)
        return IntRange(first, second, lx.pos)
    if isinstance(first, Ident):
        # A bare ident argument: keep as TypeExpr so the compiler can decide
        # whether it names a type or a constant.
        return TypeExpr(first.name, pos=first.pos)
    return first


def _parse_fields_inline(lx: _Lexer, terminator: str) -> List[Field]:
    fields: List[Field] = []
    if lx.accept("punct", terminator):
        return fields
    while True:
        fname = lx.expect("ident")[1]
        ftyp = _parse_type(lx)
        fields.append(Field(fname, ftyp, lx.pos))
        if lx.accept("punct", terminator):
            return fields
        lx.expect("punct", ",")


def parse(text: str, filename: str = "<input>") -> Description:
    desc = Description()
    lines = text.split("\n")
    i = 0
    while i < len(lines):
        pos = Pos(filename, i + 1)
        raw = lines[i]
        i += 1
        stripped = raw.split("#", 1)[0].strip() if '"' not in raw else raw.strip()
        if not stripped or stripped.startswith("#"):
            continue

        # include / incdir / define are keyword-prefixed raw lines.
        first_word = stripped.split(None, 1)[0]
        if first_word in ("include", "incdir"):
            m = re.match(r"(?:include|incdir)\s*<([^>]*)>", stripped)
            if not m:
                raise ParseError(pos, f"malformed {first_word}")
            desc.nodes.append(IncludeDef(m.group(1), pos))
            continue
        if first_word == "define":
            rest = stripped.split(None, 2)
            if len(rest) < 3:
                raise ParseError(pos, "malformed define")
            desc.nodes.append(DefineDef(rest[1], rest[2].split("#")[0].strip(), pos))
            continue

        lx = _Lexer(raw, pos)
        if lx.eol:
            continue

        if lx.accept("ident", "resource"):
            name = lx.expect("ident")[1]
            lx.expect("punct", "[")
            base = _parse_type(lx)
            lx.expect("punct", "]")
            values: List = []
            if lx.accept("punct", ":"):
                while True:
                    values.append(_parse_expr(lx))
                    if not lx.accept("punct", ","):
                        break
            desc.nodes.append(ResourceDef(name, base, values, pos))
            continue

        name = lx.expect("ident")[1]
        t = lx.peek()

        if t and t == ("punct", "("):
            # syscall definition
            lx.next()
            fields = _parse_fields_inline(lx, ")")
            ret = None
            if not lx.eol:
                ret = _parse_type(lx)
            call_name = name.split("$", 1)[0]
            desc.nodes.append(CallDef(name, call_name, fields, ret, pos))
            continue

        if t and t == ("punct", "="):
            # flags or string-flags
            lx.next()
            vals: List = []
            is_str = False
            while True:
                tok = lx.next()
                if tok[0] == "string":
                    is_str = True
                    vals.append(tok[1])
                elif tok[0] == "number":
                    vals.append(IntLit(tok[1], pos))
                elif tok[0] == "ident":
                    vals.append(Ident(tok[1], pos))
                else:
                    raise ParseError(pos, f"bad flag value {tok[1]!r}")
                if not lx.accept("punct", ","):
                    break
                # a trailing ',' continues the list on following lines;
                # skip blank/comment-only continuation lines
                while lx.eol and i < len(lines):
                    lx = _Lexer(lines[i], Pos(filename, i + 1))
                    i += 1
            if is_str:
                if any(not isinstance(v, str) for v in vals):
                    raise ParseError(
                        pos, f"flag list {name} mixes strings and integers")
                desc.nodes.append(StrFlagsDef(name, list(vals), pos))
            else:
                desc.nodes.append(FlagsDef(name, vals, pos))
            continue

        if t and (t == ("punct", "{") or t == ("punct", "[")):
            is_union = t[1] == "["
            closer = "]" if is_union else "}"
            lx.next()
            fields: List[Field] = []
            attrs: List[str] = []
            while True:
                if i >= len(lines):
                    raise ParseError(pos, f"unterminated {'union' if is_union else 'struct'} {name}")
                fpos = Pos(filename, i + 1)
                fline = lines[i]
                i += 1
                body = fline.split("#", 1)[0].strip() if '"' not in fline else fline.strip()
                if not body:
                    continue
                flx = _Lexer(fline, fpos)
                if flx.accept("punct", closer):
                    # optional attribute list: } [packed, align_4]
                    if flx.accept("punct", "["):
                        while True:
                            attrs.append(flx.expect("ident")[1])
                            if flx.accept("punct", "]"):
                                break
                            flx.expect("punct", ",")
                    break
                fname = flx.expect("ident")[1]
                ftyp = _parse_type(flx)
                fields.append(Field(fname, ftyp, fpos))
            desc.nodes.append(StructDef(name, fields, is_union, attrs, pos))
            continue

        raise ParseError(pos, f"cannot parse line starting with {name!r}")

    return desc


def parse_files(paths) -> Description:
    desc = Description()
    for p in paths:
        with open(p) as f:
            desc.extend(parse(f.read(), str(p)))
    return desc
