"""AST for the syscall description language.

The language is source-compatible with the reference's description syntax
(reference: /root/reference/pkg/ast/parser.go, /root/reference/sys/linux/*.txt)
so existing description corpora can be brought over: resources, flags,
string-flags, structs/unions with attributes, syscall variants (`name$tag`),
and the builtin type constructors (ptr, array, buffer, string, filename, len,
bytesize, const, flags, proc, csum, vma, text, int*).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union


@dataclass
class Pos:
    file: str = ""
    line: int = 0

    def __str__(self) -> str:
        return f"{self.file}:{self.line}"


@dataclass
class IntLit:
    value: int
    pos: Pos = field(default_factory=Pos)


@dataclass
class StrLit:
    value: str
    pos: Pos = field(default_factory=Pos)


@dataclass
class IntRange:
    begin: "Expr"
    end: "Expr"
    pos: Pos = field(default_factory=Pos)


@dataclass
class Ident:
    name: str
    pos: Pos = field(default_factory=Pos)


# A constant expression: literal int or symbolic const name.
Expr = Union[IntLit, Ident]


@dataclass
class TypeExpr:
    """`name[arg, arg, ...]:bitfield_len` — args may themselves be types,
    literals, or ranges."""

    name: str
    args: List[Union["TypeExpr", IntLit, StrLit, IntRange, Ident]] = field(
        default_factory=list)
    bitfield_len: Optional[Expr] = None
    pos: Pos = field(default_factory=Pos)


@dataclass
class Field:
    name: str
    typ: TypeExpr
    pos: Pos = field(default_factory=Pos)


@dataclass
class CallDef:
    name: str  # full variant name, e.g. "open$dir"
    call_name: str  # base, e.g. "open"
    fields: List[Field] = field(default_factory=list)
    ret: Optional[TypeExpr] = None
    pos: Pos = field(default_factory=Pos)


@dataclass
class ResourceDef:
    name: str
    base: TypeExpr = None
    values: List[Expr] = field(default_factory=list)
    pos: Pos = field(default_factory=Pos)


@dataclass
class FlagsDef:
    name: str
    values: List[Expr] = field(default_factory=list)
    pos: Pos = field(default_factory=Pos)


@dataclass
class StrFlagsDef:
    name: str
    values: List[str] = field(default_factory=list)
    pos: Pos = field(default_factory=Pos)


@dataclass
class StructDef:
    name: str
    fields: List[Field] = field(default_factory=list)
    is_union: bool = False
    attrs: List[str] = field(default_factory=list)
    pos: Pos = field(default_factory=Pos)


@dataclass
class DefineDef:
    name: str
    expr: str  # raw expression text, resolved against the const table
    pos: Pos = field(default_factory=Pos)


@dataclass
class IncludeDef:
    path: str
    pos: Pos = field(default_factory=Pos)


Node = Union[CallDef, ResourceDef, FlagsDef, StrFlagsDef, StructDef,
             DefineDef, IncludeDef]


@dataclass
class Description:
    nodes: List[Node] = field(default_factory=list)

    def extend(self, other: "Description") -> None:
        self.nodes.extend(other.nodes)
