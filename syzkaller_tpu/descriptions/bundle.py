"""Shared scaffolding for bundled per-OS description packages.

Each descriptions/<os>/ package (linux, freebsd, fuchsia, windows) bundles
description .txt files + consts_<arch>.json and registers a Target on
demand — the role of the reference's generated sys/<os>/<arch>.go init()
(reference: /root/reference/sys/linux/amd64.go:6-8).  The load/parse/
compile/register flow is identical across OSes; only the arch hooks and
(for vDSO/PE-dispatched OSes) the call-ordinal base differ.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Optional

from ..prog.target import Target, register_target, _targets
from .ast import CallDef
from .compiler import compile_description
from .parser import parse_files


class UnsupportedArchError(KeyError):
    """Raised when a bundled OS package has no consts for the arch."""


def build_bundled_target(
    os: str,
    arch: str,
    here: Path,
    *,
    init_arch: Callable[[Target], None],
    ptr_size: int = 8,
    page_size: int = 4 << 10,
    data_offset: int = 512 << 20,
    num_pages: int = 4 << 10,
    ordinal_base: Optional[int] = None,
) -> Target:
    """Compile a bundled descriptions directory into a registered-ready Target.

    ordinal_base: for OSes whose calls are dispatched by name (zircon vDSO,
    PE imports) rather than numbered traps, assign each non-syz_* call a
    stable ordinal `ordinal_base + index of call name in sorted order`
    instead of requiring __NR_* consts.
    """
    consts_path = here / f"consts_{arch}.json"
    if not consts_path.exists():
        raise UnsupportedArchError(
            f"{os}/{arch}: no bundled consts ({consts_path.name}); "
            f"available: {sorted(p.name for p in here.glob('consts_*.json'))}")
    consts = json.loads(consts_path.read_text())
    desc = parse_files(sorted(here.glob("*.txt")))
    if ordinal_base is not None:
        names = sorted({n.call_name for n in desc.nodes
                        if isinstance(n, CallDef)
                        and not n.call_name.startswith("syz_")})
        for i, name in enumerate(names):
            consts.setdefault(f"__NR_{name}", ordinal_base + i)
    target = compile_description(desc, consts, os=os, arch=arch,
                                 ptr_size=ptr_size, page_size=page_size)
    target.data_offset = data_offset
    target.num_pages = num_pages
    init_arch(target)
    return target


def ensure_bundled_registered(
    os: str, arch: str, build: Callable[[str], Target]) -> Target:
    key = f"{os}/{arch}"
    if key not in _targets:
        register_target(build(arch))
    return _targets[key]
