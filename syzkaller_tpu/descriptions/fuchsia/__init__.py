"""Bundled fuchsia/amd64 target: zircon descriptions + arch hooks.

Plays the role of the reference's sys/fuchsia target (generated
sys/fuchsia/{amd64,arm64}.go + init.go; reference:
/root/reference/sys/fuchsia/init.go:10-50).  Zircon syscalls are vDSO
entry points rather than numbered traps, so instead of `__NR_*` consts the
target assigns each `zx_*` call a stable ordinal (VDSO_BASE + index of the
call name in sorted order) — an executor for fuchsia dispatches through a
name-indexed vDSO table exactly the way the reference's generated
syscalls_fuchsia.h table does.  The memory bootstrap call is `syz_mmap`
(maps zero-filled pages into the root vmar), matching the reference.
"""

from __future__ import annotations

from pathlib import Path

from ...prog import prog as progmod
from ...prog.target import Target
from ..bundle import build_bundled_target, ensure_bundled_registered

_HERE = Path(__file__).parent

VDSO_BASE = 1 << 20

STRING_DICTIONARY = [
    "zircon", "mxio", "devmgr", "svchost", "driver", "channel",
]


def build_target(arch: str = "amd64") -> Target:
    return build_bundled_target("fuchsia", arch, _HERE,
                                init_arch=_init_arch,
                                ordinal_base=VDSO_BASE)


def _init_arch(target: Target) -> None:
    mmap = target.syscall_map.get("syz_mmap")

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=mmap,
            args=[
                progmod.PointerArg(mmap.args[0], start, 0, npages, None),
                progmod.ConstArg(mmap.args[1], npages * target.page_size),
            ],
            ret=progmod.ReturnArg(mmap.ret) if mmap.ret else progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        if c.meta.name == "syz_mmap":
            npages = c.args[1].val // target.page_size
            return c.args[0].page_index, npages, npages > 0
        return 0, 0, False

    def sanitize_call(c: progmod.Call) -> None:
        # Exit statuses 67/68 are reserved by the executor protocol
        # (executor.cc kStatusFailed/kStatusHanged magic).
        if c.meta.call_name == "zx_process_exit" and c.args:
            if c.args[0].val % 128 in (67, 68):
                c.args[0].val = 1

    if mmap is not None:
        target.mmap_syscall = mmap
        target.make_mmap = make_mmap
        target.analyze_mmap = analyze_mmap
    target.sanitize_call = sanitize_call
    target.string_dictionary = list(STRING_DICTIONARY)


def ensure_registered(arch: str = "amd64") -> Target:
    return ensure_bundled_registered("fuchsia", arch, build_target)
