"""Bundled linux target: descriptions + consts + arch hooks.

Plays the role of the reference's generated sys/linux/<arch>.go +
sys/linux/init.go (reference: /root/reference/sys/linux/init.go:12-60,148):
compiles the bundled description files at first use and registers a Target
with the mmap/sanitize hooks wired in.
"""

from __future__ import annotations

from pathlib import Path

from ...prog import prog as progmod
from ...prog.target import Target
from ..bundle import build_bundled_target, ensure_bundled_registered

_HERE = Path(__file__).parent

STRING_DICTIONARY = [
    "user", "self", "proc", "sysfs", "cgroup", "tmpfs", "lo", "eth0",
    "wlan0", "ppp0", "nodev", "security", "trusted", "system", "keyring",
    "GPL", "md5sum", "mime_type",
]


def build_target(arch: str = "amd64") -> Target:
    return build_bundled_target("linux", arch, _HERE, init_arch=_init_arch)


def _init_arch(target: Target) -> None:
    mmap = target.syscall_map.get("mmap")
    target.mmap_syscall = mmap
    cm = target.consts
    prot_rw = cm["PROT_READ"] | cm["PROT_WRITE"]
    map_flags = cm["MAP_ANONYMOUS"] | cm["MAP_PRIVATE"] | cm["MAP_FIXED"]
    invalid_fd = (1 << 64) - 1

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=mmap,
            args=[
                progmod.PointerArg(mmap.args[0], start, 0, npages, None),
                progmod.ConstArg(mmap.args[1], npages * target.page_size),
                progmod.ConstArg(mmap.args[2], prot_rw),
                progmod.ConstArg(mmap.args[3], map_flags),
                progmod.make_result_arg(mmap.args[4], None, invalid_fd),
                progmod.ConstArg(mmap.args[5], 0),
            ],
            ret=progmod.ReturnArg(mmap.ret) if mmap.ret else progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        name = c.meta.name
        if name == "mmap":
            npages = c.args[1].val // target.page_size
            if npages == 0:
                return 0, 0, False
            flags = c.args[3].val
            fd_val = getattr(c.args[4], "val", 0)
            if flags & cm["MAP_ANONYMOUS"] == 0 and fd_val == invalid_fd:
                return 0, 0, False
            return c.args[0].page_index, npages, True
        if name == "munmap":
            return c.args[0].page_index, c.args[1].val // target.page_size, False
        if name == "mremap":
            return c.args[4].page_index, c.args[2].val // target.page_size, True
        return 0, 0, False

    def sanitize_call(c: progmod.Call) -> None:
        cn = c.meta.call_name
        if cn == "mmap":
            # Force MAP_FIXED for deterministic replay.
            c.args[3].val |= cm["MAP_FIXED"]
        elif cn == "mremap":
            if c.args[3].val & cm["MREMAP_MAYMOVE"]:
                c.args[3].val |= cm["MREMAP_FIXED"]
        elif cn in ("exit", "exit_group"):
            # Status codes 67/68 are reserved by the executor protocol.
            if c.args and c.args[0].val % 128 in (67, 68):
                c.args[0].val = 1

    if mmap is not None:
        target.make_mmap = make_mmap
        target.analyze_mmap = analyze_mmap
    target.sanitize_call = sanitize_call
    target.string_dictionary = list(STRING_DICTIONARY)
    _register_special_structs(target)


def _register_special_structs(target: Target) -> None:
    """timespec/timeval generators (reference sys/linux/init.go:214-280):
    random struct bytes would make every timeout-taking call block forever
    or return instantly at random, so generate values that are (1) now/past,
    (2) a few ms ahead (straddling the executor's 20ms call timeout: both
    10ms and 30ms), (3) unreachable future, or (4) absolute few-ms-ahead by
    chaining a clock_gettime(CLOCK_REALTIME) call and adding the delta via
    the exec-format result ops (op_div/op_add)."""
    cg = target.syscall_map.get("clock_gettime")
    clock_realtime = target.consts.get("CLOCK_REALTIME", 0)

    def gen_time(r, s, typ, old):
        usec = typ.name == "timeval"
        f0, f1 = typ.fields[0], typ.fields[1]
        calls: list = []
        if r.n_out_of(1, 4):
            # Now for relative, past for absolute.
            inner = [progmod.make_result_arg(f0, None, 0),
                     progmod.make_result_arg(f1, None, 0)]
        elif r.n_out_of(1, 3):
            # Few ms ahead for relative, past for absolute.
            nsec = 10_000_000 if r.n_out_of(1, 2) else 30_000_000
            if usec:
                nsec //= 1000
            inner = [progmod.make_result_arg(f0, None, 0),
                     progmod.make_result_arg(f1, None, nsec)]
        elif r.n_out_of(1, 2) or cg is None:
            # Unreachable future for both relative and absolute.
            inner = [progmod.make_result_arg(f0, None, 2 * 10**9),
                     progmod.make_result_arg(f1, None, 0)]
        else:
            # Few ms ahead for absolute: clock_gettime(REALTIME, &tp),
            # then sec=tp.sec, nsec=tp.nsec/op_div+op_add.
            ptr_t = cg.args[1]
            ts_t = ptr_t.elem
            tp_inner = [progmod.make_result_arg(ts_t.fields[0], None, 0),
                        progmod.make_result_arg(ts_t.fields[1], None, 0)]
            tp = progmod.GroupArg(ts_t, tp_inner)
            tpaddr, calls = r.alloc(s, ptr_t, tp.size(), tp)
            calls = list(calls) + [progmod.Call(
                meta=cg,
                args=[progmod.ConstArg(cg.args[0], clock_realtime), tpaddr],
                ret=progmod.ReturnArg(cg.ret))]
            msec = 10 if r.n_out_of(1, 2) else 30
            sec = progmod.make_result_arg(f0, tp_inner[0], 0)
            if usec:
                nsec = progmod.ResultArg(f1, res=tp_inner[1],
                                         op_div=1000, op_add=msec * 1000)
            else:
                nsec = progmod.ResultArg(f1, res=tp_inner[1],
                                         op_add=msec * 1_000_000)
            tp_inner[1].uses.add(nsec)
            inner = [sec, nsec]
        return progmod.GroupArg(typ, inner), calls

    target.special_structs = {"timespec": gen_time, "timeval": gen_time}


def ensure_registered(arch: str = "amd64") -> Target:
    return ensure_bundled_registered("linux", arch, build_target)
