"""Bundled linux target: descriptions + consts + arch hooks.

Plays the role of the reference's generated sys/linux/<arch>.go +
sys/linux/init.go (reference: /root/reference/sys/linux/init.go:12-60,148):
compiles the bundled description files at first use and registers a Target
with the mmap/sanitize hooks wired in.
"""

from __future__ import annotations

import json
from pathlib import Path

from ...prog import prog as progmod
from ...prog.target import Target, register_target, _targets
from ...prog.types import Dir
from ..compiler import compile_description
from ..parser import parse_files

_HERE = Path(__file__).parent

DATA_OFFSET = 512 << 20
PAGE_SIZE = 4 << 10
NUM_PAGES = 4 << 10

STRING_DICTIONARY = [
    "user", "self", "proc", "sysfs", "cgroup", "tmpfs", "lo", "eth0",
    "wlan0", "ppp0", "nodev", "security", "trusted", "system", "keyring",
    "GPL", "md5sum", "mime_type",
]


def build_target(arch: str = "amd64") -> Target:
    consts = json.loads((_HERE / f"consts_{arch}.json").read_text())
    desc = parse_files(sorted(_HERE.glob("*.txt")))
    target = compile_description(desc, consts, os="linux", arch=arch,
                                 ptr_size=8, page_size=PAGE_SIZE)
    target.data_offset = DATA_OFFSET
    target.num_pages = NUM_PAGES
    _init_arch(target)
    return target


def _init_arch(target: Target) -> None:
    mmap = target.syscall_map.get("mmap")
    target.mmap_syscall = mmap
    cm = target.consts
    prot_rw = cm["PROT_READ"] | cm["PROT_WRITE"]
    map_flags = cm["MAP_ANONYMOUS"] | cm["MAP_PRIVATE"] | cm["MAP_FIXED"]
    invalid_fd = (1 << 64) - 1

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=mmap,
            args=[
                progmod.PointerArg(mmap.args[0], start, 0, npages, None),
                progmod.ConstArg(mmap.args[1], npages * target.page_size),
                progmod.ConstArg(mmap.args[2], prot_rw),
                progmod.ConstArg(mmap.args[3], map_flags),
                progmod.make_result_arg(mmap.args[4], None, invalid_fd),
                progmod.ConstArg(mmap.args[5], 0),
            ],
            ret=progmod.ReturnArg(mmap.ret) if mmap.ret else progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        name = c.meta.name
        if name == "mmap":
            npages = c.args[1].val // target.page_size
            if npages == 0:
                return 0, 0, False
            flags = c.args[3].val
            fd_val = getattr(c.args[4], "val", 0)
            if flags & cm["MAP_ANONYMOUS"] == 0 and fd_val == invalid_fd:
                return 0, 0, False
            return c.args[0].page_index, npages, True
        if name == "munmap":
            return c.args[0].page_index, c.args[1].val // target.page_size, False
        if name == "mremap":
            return c.args[4].page_index, c.args[2].val // target.page_size, True
        return 0, 0, False

    def sanitize_call(c: progmod.Call) -> None:
        cn = c.meta.call_name
        if cn == "mmap":
            # Force MAP_FIXED for deterministic replay.
            c.args[3].val |= cm["MAP_FIXED"]
        elif cn == "mremap":
            if c.args[3].val & cm["MREMAP_MAYMOVE"]:
                c.args[3].val |= cm["MREMAP_FIXED"]
        elif cn in ("exit", "exit_group"):
            # Status codes 67/68 are reserved by the executor protocol.
            if c.args and c.args[0].val % 128 in (67, 68):
                c.args[0].val = 1

    if mmap is not None:
        target.make_mmap = make_mmap
        target.analyze_mmap = analyze_mmap
    target.sanitize_call = sanitize_call
    target.string_dictionary = list(STRING_DICTIONARY)


def ensure_registered(arch: str = "amd64") -> Target:
    key = f"linux/{arch}"
    if key not in _targets:
        register_target(build_target(arch))
    return _targets[key]
