"""Bundled windows/amd64 target: Win32 descriptions + arch hooks.

Plays the role of the reference's sys/windows target (generated
sys/windows/amd64.go + init.go; reference:
/root/reference/sys/windows/init.go:10-66).  VirtualAlloc is the target's
mmap: make_mmap emits `VirtualAlloc(addr, size, MEM_COMMIT|MEM_RESERVE,
PAGE_EXECUTE_READWRITE)` and analyze_mmap treats every VirtualAlloc as a
mapping, mirroring the reference's makeMmap/analyzeMmap.  Win32 calls are
dispatched by name through the PE import table, so the target assigns each
call a stable ordinal (IMPORT_BASE + index in sorted order).
"""

from __future__ import annotations

from pathlib import Path

from ...prog import prog as progmod
from ...prog.target import Target
from ..bundle import build_bundled_target, ensure_bundled_registered

_HERE = Path(__file__).parent

IMPORT_BASE = 1 << 21

STRING_DICTIONARY = [
    "Global", "Local", "Software", "System", "CurrentControlSet",
    "\\\\.\\pipe\\syz", "MACHINE",
]


def build_target(arch: str = "amd64") -> Target:
    return build_bundled_target("windows", arch, _HERE,
                                init_arch=_init_arch,
                                ordinal_base=IMPORT_BASE)


def _init_arch(target: Target) -> None:
    valloc = target.syscall_map.get("VirtualAlloc")
    cm = target.consts
    alloc_type = cm["MEM_COMMIT"] | cm["MEM_RESERVE"]
    prot = cm["PAGE_EXECUTE_READWRITE"]

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=valloc,
            args=[
                progmod.PointerArg(valloc.args[0], start, 0, npages, None),
                progmod.ConstArg(valloc.args[1], npages * target.page_size),
                progmod.ConstArg(valloc.args[2], alloc_type),
                progmod.ConstArg(valloc.args[3], prot),
            ],
            ret=progmod.ReturnArg(valloc.ret) if valloc.ret else progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        if c.meta.name == "VirtualAlloc":
            npages = c.args[1].val // target.page_size
            return c.args[0].page_index, npages, npages > 0
        if c.meta.name == "VirtualFree":
            return c.args[0].page_index, c.args[1].val // target.page_size, False
        return 0, 0, False

    if valloc is not None:
        target.mmap_syscall = valloc
        target.make_mmap = make_mmap
        target.analyze_mmap = analyze_mmap
    target.string_dictionary = list(STRING_DICTIONARY)


def ensure_registered(arch: str = "amd64") -> Target:
    return ensure_bundled_registered("windows", arch, build_target)
