"""Constant extraction against real kernel headers (syz-extract equivalent).

Plays the role of the reference's sys/syz-extract (reference:
/root/reference/sys/syz-extract/fetch.go:20-95): for every constant
identifier a description file references (flag values, const[...] args,
resource seed values, plus the __NR_* number of every non-pseudo syscall),
compile a C probe that prints the values, and merge them into
consts_<arch>.json.

Unresolvable identifiers are discovered the same way the reference does it:
compile, parse the compiler's "'FOO' undeclared" diagnostics, drop those
names, retry.  Calls whose __NR_* is missing simply stay unsupported at
compile time (compiler.py records them), matching the reference's
disabled-syscall behavior.

Usage:  python -m syzkaller_tpu.descriptions.extract [--arch amd64] [files...]
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path
from typing import Dict, Iterable, List, Set, Tuple

from . import ast
from .parser import parse_files

DEFAULT_INCLUDES = [
    "sys/syscall.h",
    "sys/types.h",
    "sys/stat.h",
    "sys/mman.h",
    "sys/socket.h",
    "sys/ioctl.h",
    "sys/time.h",
    "sys/resource.h",
    "sys/wait.h",
    "fcntl.h",
    "unistd.h",
    "signal.h",
    "sched.h",
    "errno.h",
]

_UNDECLARED_RE = re.compile(
    # gcc: "'FOO' undeclared"; clang: "use of undeclared identifier 'FOO'"
    r"['‘]([A-Za-z_][A-Za-z0-9_]*)['’] undeclared"
    r"|undeclared identifier ['‘]([A-Za-z_][A-Za-z0-9_]*)['’]")


def _undeclared(stderr: str):
    return {a or b for a, b in _UNDECLARED_RE.findall(stderr)}

# Type-language keywords that can appear as bare ident args but never name
# C constants (ptr/buffer directions, int bases, builtin types).
_TYPE_KEYWORDS = {
    "in", "out", "inout", "opt", "intptr", "int8", "int16", "int32", "int64",
    "int16be", "int32be", "int64be", "bool8", "const", "flags", "len",
    "bytesize", "bytesize2", "bytesize4", "bytesize8", "proc", "csum",
    "inet", "pseudo", "fileoff", "vma", "ptr", "buffer", "string",
    "stringnoz", "filename", "text", "array", "parent",
    "x86_real", "x86_16", "x86_32", "x86_64", "arm64",
}


def collect_idents(desc: ast.Description) -> Tuple[Set[str], Set[str], List[str]]:
    """Returns (const_names, syscall_names, includes) referenced by `desc`."""
    consts: Set[str] = set()
    calls: Set[str] = set()
    includes: List[str] = []

    def walk_type(te: ast.TypeExpr) -> None:
        args = te.args
        # len[field]/bytesize[field]/csum[field,...] name sibling FIELDS in
        # their first arg, not constants.
        if te.name in ("len", "bytesize", "bytesize2", "bytesize4",
                       "bytesize8", "csum") and args:
            args = args[1:]
        for a in args:
            if isinstance(a, ast.Ident):
                consts.add(a.name)
            elif isinstance(a, ast.IntRange):
                for e in (a.begin, a.end):
                    if isinstance(e, ast.Ident):
                        consts.add(e.name)
            elif isinstance(a, ast.TypeExpr):
                # A bare ident arg parses as an argless TypeExpr; it may name
                # a constant (const[IPC_STAT]) — probe everything that isn't
                # a type keyword, locally-defined type, or flag-set name.
                if not a.args and a.bitfield_len is None \
                        and a.name not in _TYPE_KEYWORDS:
                    consts.add(a.name)
                walk_type(a)
        if isinstance(te.bitfield_len, ast.Ident):
            consts.add(te.bitfield_len.name)

    for n in desc.nodes:
        if isinstance(n, ast.IncludeDef):
            includes.append(n.path)
        elif isinstance(n, ast.FlagsDef):
            for v in n.values:
                if isinstance(v, ast.Ident):
                    consts.add(v.name)
        elif isinstance(n, ast.ResourceDef):
            walk_type(n.base)
            for v in n.values:
                if isinstance(v, ast.Ident):
                    consts.add(v.name)
        elif isinstance(n, ast.CallDef):
            if not n.call_name.startswith("syz_"):
                calls.add(n.call_name)
            for f in n.fields:
                walk_type(f.typ)
            if n.ret is not None:
                walk_type(n.ret)
        elif isinstance(n, ast.StructDef):
            for f in n.fields:
                walk_type(f.typ)
        elif isinstance(n, ast.DefineDef):
            # define bodies are resolved by the compiler against consts;
            # pull bare idents out of the expression too.
            for m in re.finditer(r"(?<![0-9a-zA-Z_])[A-Za-z_][A-Za-z0-9_]*",
                                 n.expr):
                consts.add(m.group())

    # Type keywords & flag-set names leak in via bare-ident heuristics
    # upstream; filter anything that is locally defined in the descriptions.
    local = set()
    for n in desc.nodes:
        if isinstance(n, (ast.FlagsDef, ast.StrFlagsDef, ast.StructDef,
                          ast.ResourceDef, ast.DefineDef)):
            local.add(n.name)
    consts -= local
    return consts, calls, includes


def _probe_source(names: List[str], includes: Iterable[str]) -> str:
    lines = ["#define _GNU_SOURCE"]
    # Kernel uapi headers routinely assume the libc base types are already
    # in scope (uint8_t, struct sockaddr_storage, ...), so the preamble
    # must precede the description's own include list.
    for inc in ("stdint.h", "stddef.h", "sys/types.h", "sys/socket.h"):
        lines.append(f"#include <{inc}>")
    for inc in includes:
        lines.append(f"#include <{inc}>")
    lines.append("#include <stdio.h>")
    lines.append("int main(void) {")
    for n in names:
        lines.append(
            f'    printf("{n} %lld\\n", (long long)({n}));')
    lines.append("    return 0;")
    lines.append("}")
    return "\n".join(lines) + "\n"


def extract_consts(names: Set[str], includes: List[str],
                   cc: str = "gcc") -> Tuple[Dict[str, int], Set[str]]:
    """Compile-and-run probe; returns (values, unresolved)."""
    remaining = sorted(names)
    unresolved: Set[str] = set()
    incs = includes + [i for i in DEFAULT_INCLUDES if i not in includes]
    with tempfile.TemporaryDirectory() as td:
        src = Path(td) / "probe.c"
        binp = Path(td) / "probe"
        compiled = False
        last_err = ""
        for _ in range(len(names) + 2):
            if not remaining:
                return {}, unresolved
            src.write_text(_probe_source(remaining, incs))
            r = subprocess.run([cc, str(src), "-o", str(binp), "-w"],
                               capture_output=True, text=True)
            if r.returncode == 0:
                compiled = True
                break
            last_err = r.stderr
            bad = _undeclared(r.stderr)
            # Type names used as values (e.g. a struct name leaking in) fail
            # with "expected expression before 'name'" instead of undeclared.
            bad |= set(re.findall(
                r"expected expression before ['‘]([A-Za-z_][A-Za-z0-9_]*)['’]",
                r.stderr))
            bad &= set(remaining)
            if not bad:
                raise RuntimeError(
                    f"const probe failed to compile:\n{r.stderr[:2000]}")
            unresolved |= bad
            remaining = [n for n in remaining if n not in bad]
        if not compiled:
            raise RuntimeError(
                "const probe never compiled after pruning; last compiler "
                f"output:\n{last_err[:2000]}")
        out = subprocess.run([str(binp)], capture_output=True, text=True,
                             check=True).stdout
    vals: Dict[str, int] = {}
    for line in out.splitlines():
        name, v = line.rsplit(" ", 1)
        vals[name] = int(v)
    return vals, unresolved


def extract_for_files(paths: List[Path], cc: str = "gcc"):
    """Extract consts for description files, each with its own includes."""
    # Names defined in ANY file (structs/resources/flag-sets/defines) are
    # description-language symbols, not C constants — filter them globally
    # so cross-file type references don't leak into the probes.
    all_desc = parse_files(paths)
    global_local: Set[str] = set()
    for n in all_desc.nodes:
        if isinstance(n, (ast.FlagsDef, ast.StrFlagsDef, ast.StructDef,
                          ast.ResourceDef, ast.DefineDef)):
            global_local.add(n.name)
    merged: Dict[str, int] = {}
    unresolved: Set[str] = set()
    for p in paths:
        desc = parse_files([p])
        consts, calls, includes = collect_idents(desc)
        names = (set(consts) - global_local) | {f"__NR_{c}" for c in calls}
        vals, unres = extract_consts(names, includes, cc=cc)
        merged.update(vals)
        unresolved |= unres
    unresolved -= set(merged)
    return merged, unresolved


_HOST_ARCH = {"x86_64": "amd64", "aarch64": "arm64", "i686": "386",
              "i386": "386", "ppc64le": "ppc64le", "riscv64": "riscv64"}


_ASM_GENERIC = Path("/usr/include/asm-generic")


def derive_arm64(base: Dict[str, int]) -> Dict[str, int]:
    """Derive linux/arm64 consts from amd64 ones + asm-generic headers.

    arm64 takes its syscall table and fcntl flag values verbatim from the
    asm-generic headers (arch/arm64/include/uapi/asm/unistd.h is a
    one-line include of asm-generic/unistd.h), so those headers — present
    on any linux host — are the authoritative arm64 ABI even without an
    aarch64 cross compiler.  Everything else (socket/ioctl/mman/signal
    values) is identical between the two arches, both already using the
    asm-generic definitions.  Legacy calls with no arm64 trap (open, pipe,
    dup2, rename, poll, ...) get no __NR_* entry and stay unsupported at
    compile time, matching real arm64 kernels.

    Non-__NR_ consts are copied wholesale, so x86-only values (ARCH_SET_GS
    and friends) ride along inert: the compiler only reads consts that a
    supported call's types reference, and x86-only calls are already
    excluded by their missing __NR_* entry.
    """
    out = {k: v for k, v in base.items() if not k.startswith("__NR_")}

    # Run the real preprocessor over asm-generic/unistd.h with arm64's
    # configuration (__BITS_PER_LONG=64 plus the __ARCH_WANT_* switches
    # arm64's uapi unistd.h sets), so 32-bit-only traps (clock_gettime64,
    # futex_time64, ...) and unconfigured optional ones are excluded by
    # their #if guards instead of leaking into the table.
    cpp = subprocess.run(
        ["gcc", "-E", "-dM", "-x", "c",
         "-D__BITS_PER_LONG=64",
         "-D__ARCH_WANT_NEW_STAT", "-D__ARCH_WANT_RENAMEAT",
         "-D__ARCH_WANT_SET_GET_RLIMIT", "-D__ARCH_WANT_SYS_CLONE3",
         "-D__ARCH_WANT_MEMFD_SECRET",
         str(_ASM_GENERIC / "unistd.h")],
        capture_output=True, text=True, check=True).stdout
    defs: Dict[str, str] = {}
    for m in re.finditer(r"#define\s+(__NR3264_\w+|__NR_\w+)\s+(\S+)", cpp):
        defs[m.group(1)] = m.group(2)
    for name, val in defs.items():
        if not name.startswith("__NR_"):
            continue
        val = defs.get(val, val)  # __NR_mmap -> __NR3264_mmap -> 222
        if val.isdigit():
            out.setdefault(name, int(val))
    out.pop("__NR_syscalls", None)  # table size, not a trap
    out.pop("__NR_arch_specific_syscall", None)

    # Same trap, different name: amd64's newfstatat is asm-generic's
    # fstatat (__NR3264_fstatat).
    if "__NR_fstatat" in out:
        out.setdefault("__NR_newfstatat", out["__NR_fstatat"])

    # arm64 does NOT take fcntl flags from asm-generic: it inherits arm's
    # arch overrides (arch/arm64/include/uapi/asm/fcntl.h) for these four.
    out.update({
        "O_DIRECTORY": 0o40000,
        "O_NOFOLLOW": 0o100000,
        "O_DIRECT": 0o200000,
        "O_LARGEFILE": 0o400000,
    })
    return out


def main(argv: List[str]) -> int:
    arch = "amd64"
    cc = None
    derive = False
    args = []
    it = iter(argv)
    for a in it:
        if a == "--arch":
            arch = next(it)
        elif a == "--cc":
            cc = next(it)
        elif a == "--derive-arm64":
            derive = True
        else:
            args.append(a)
    if derive:
        here = Path(__file__).parent / "linux"
        base = json.loads((here / "consts_amd64.json").read_text())
        vals = derive_arm64(base)
        out_path = here / "consts_arm64.json"
        out_path.write_text(json.dumps(vals, indent=1, sort_keys=True) + "\n")
        print(f"derived {len(vals)} consts -> {out_path}")
        return 0
    import platform

    host = _HOST_ARCH.get(platform.machine(), platform.machine())
    if cc is None:
        if arch != host:
            # host headers would silently yield host-arch values (wrong
            # __NR_* numbers etc.) — demand an explicit cross compiler,
            # like the reference's per-arch CC matrix (sys/targets)
            print(f"--arch {arch} != host arch {host}: pass --cc "
                  f"<cross-gcc> targeting {arch}", file=sys.stderr)
            return 1
        cc = "gcc"
    here = Path(__file__).parent / "linux"
    paths = [Path(a) for a in args] or sorted(here.glob("*.txt"))
    out_path = here / f"consts_{arch}.json"
    existing: Dict[str, int] = {}
    if out_path.exists():
        existing = json.loads(out_path.read_text())
    vals, unresolved = extract_for_files(paths, cc=cc)
    existing.update(vals)
    out_path.write_text(json.dumps(existing, indent=1, sort_keys=True) + "\n")
    print(f"extracted {len(vals)} consts -> {out_path}")
    if unresolved:
        print(f"unresolved ({len(unresolved)}): "
              f"{' '.join(sorted(unresolved))}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
