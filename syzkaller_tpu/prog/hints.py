"""Comparison-operand hints: turn observed kernel comparisons into mutants.

Capability parity with reference /root/reference/prog/hints.go:33-207:
the executor (KCOV_TRACE_CMP) reports every comparison `(op1, op2)` a call
performed; `CompMap` records op->comparand sets; `mutate_with_hints`
substitutes matched argument values with the comparands, modeling integer
narrowing/widening casts via `shrink_expand`.

The batched device counterpart (thousands of comp traces joined against
a candidate batch at once) lives in ops/hints.py; this module is the exact
host semantics it is parity-tested against.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Set

from .generation import SPECIAL_INTS
from .prog import Call, ConstArg, DataArg, Prog, foreach_subarg
from .types import Dir

MAX_DATA_LENGTH = 100
UINT64_MASK = (1 << 64) - 1

_SPECIAL_SET = frozenset(v & UINT64_MASK for v in SPECIAL_INTS)


class CompMap:
    """operand -> set of values it was compared against."""

    def __init__(self) -> None:
        self.ops: Dict[int, Set[int]] = {}

    def add(self, op1: int, op2: int) -> None:
        self.ops.setdefault(op1 & UINT64_MASK, set()).add(op2 & UINT64_MASK)

    def __len__(self) -> int:
        return len(self.ops)

    def comparands(self, v: int) -> Set[int]:
        return self.ops.get(v & UINT64_MASK, set())

    @classmethod
    def from_pairs(cls, pairs: Iterable) -> "CompMap":
        m = cls()
        for a, b in pairs:
            m.add(a, b)
        return m


def shrink_expand(v: int, comps: CompMap) -> Set[int]:
    """All replacement values for an argument observed as `v`.

    Models casts (reference hints.go:120-178): the kernel may compare a
    narrowed (u8/u16/u32 truncation) or sign-extended version of the
    argument, so each width variant of `v` is looked up in the comp map and
    a match splices the comparand's low `size` bits back into `v`. Matches
    whose comparand is wider than the cast are ignored, as are special
    "interesting" ints the generator already tries.
    """
    v &= UINT64_MASK
    variants: Dict[int, int] = {}  # candidate looked-up value -> cast width
    for size in (8, 16, 32):
        mask = (1 << size) - 1
        variants[v & mask] = size
        if v & (1 << (size - 1)):  # negative in this width: sign-extend
            variants[(v | ~mask) & UINT64_MASK] = size
    variants[v] = 64

    out: Set[int] = set()
    for cand, size in variants.items():
        mask = (1 << size) - 1
        for new_v in comps.comparands(cand):
            hi = new_v & ~mask & UINT64_MASK
            # comparand must fit the cast width (zero- or sign-extended)
            if hi != 0 and hi != (~mask & UINT64_MASK):
                continue
            if (new_v & mask) in _SPECIAL_SET:
                continue
            out.add(((v & ~mask) | (new_v & mask)) & UINT64_MASK)
    out.discard(v)
    return out


def _bytes_to_u64(data: bytes, i: int) -> int:
    chunk = data[i:i + 8]
    return int.from_bytes(chunk + b"\x00" * (8 - len(chunk)), "little")


def mutate_with_hints(p: Prog, comp_maps: List[CompMap],
                      exec_cb: Callable[[Prog], None]) -> int:
    """For each (call, arg) match against that call's CompMap, build a
    mutant program and hand it to `exec_cb` (reference MutateWithHints,
    hints.go:50-60). Returns the number of mutants produced."""
    count = 0
    for ci, call in enumerate(p.calls):
        if ci >= len(comp_maps):
            break
        comps = comp_maps[ci]
        if not comps or call.meta is p.target.mmap_syscall:
            continue
        count += _hint_call(p, ci, comps, exec_cb)
    return count


def _arg_occurrences(call: Call) -> List:
    """Args of a call in a stable traversal order (same order on a clone)."""
    out: List = []
    for a in call.args:
        foreach_subarg(a, lambda arg, _parent: out.append(arg))
    return out


def hint_sites(call: Call) -> List:
    """Every mutable hint site of a call as (occurrence idx, kind, byte
    offset, observed u64 value) — the one site-enumeration authority shared
    by the host path below and the device join (engine _device_hints)."""
    out: List = []
    for idx, arg in enumerate(_arg_occurrences(call)):
        if isinstance(arg, ConstArg):
            out.append((idx, "const", 0, arg.val & UINT64_MASK))
        elif isinstance(arg, DataArg) and arg.typ.dir in (Dir.IN, Dir.INOUT):
            data = bytes(arg.data)
            for off in range(min(len(data), MAX_DATA_LENGTH)):
                out.append((idx, "data", off, _bytes_to_u64(data, off)))
    return out


def apply_hint(arg, kind: str, off: int, rep: int) -> None:
    """Apply one replacer to a (cloned) site arg: const value assignment or
    an 8-byte little-endian splice into the data payload."""
    if kind == "const":
        arg.val = rep & UINT64_MASK
    else:
        data = bytearray(arg.data)
        chunk = (rep & UINT64_MASK).to_bytes(8, "little")
        n = min(8, len(data) - off)
        data[off:off + n] = chunk[:n]
        arg.data = bytes(data)


def _hint_call(p: Prog, ci: int, comps: CompMap,
               exec_cb: Callable[[Prog], None]) -> int:
    # Enumerate mutation sites on the original; apply each to a fresh clone,
    # locating the arg by occurrence index (clone preserves structure).
    mutants: List = []  # (occurrence idx, kind, byte offset, replacer)
    for idx, kind, off, val in hint_sites(p.calls[ci]):
        for rep in sorted(shrink_expand(val, comps)):
            mutants.append((idx, kind, off, rep))

    for idx, kind, off, rep in mutants:
        clone = p.clone()
        apply_hint(_arg_occurrences(clone.calls[ci])[idx], kind, off, rep)
        clone.validate()
        exec_cb(clone)
    return len(mutants)
