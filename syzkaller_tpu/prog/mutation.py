"""Host-side mutation + minimization (CPU reference implementation).

Semantics parity with reference /root/reference/prog/mutation.go:12-250
(weighted op mix: corpus splice 1/100, tail-biased call insertion 20/31,
per-type arg mutation 10/11, call removal; 13-op byte-buffer mutator) and
prog.Minimize (uber-mmap glue, back-to-front call removal, per-arg
simplification with re-validation predicate).

The hot path uses the batched device mutator (syzkaller_tpu.ops.mutation);
this module is the semantic baseline it is property-tested against, and the
minimizer (which is predicate-driven re-execution, inherently host-side).
"""

from __future__ import annotations

import struct
from typing import Callable, List, Optional, Tuple

from .analysis import State, analyze, assign_sizes_call
from .generation import RandGen
from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    UnionArg,
    default_arg,
    foreach_arg,
    foreach_subarg,
    make_result_arg,
)
from .types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    UINT64_MAX,
    UnionType,
    VmaType,
)

MAX_INC = 35


def _le(fmt: str, data: bytearray, i: int) -> int:
    return struct.unpack_from("<" + fmt, data, i)[0]


def _ple(fmt: str, data: bytearray, i: int, v: int) -> None:
    size = struct.calcsize(fmt)
    struct.pack_into("<" + fmt, data, i, v & ((1 << (8 * size)) - 1))


def _be_add(data: bytearray, i: int, width: int, delta: int) -> None:
    fmt = {2: "H", 4: "I", 8: "Q"}[width]
    v = struct.unpack_from(">" + fmt, data, i)[0]
    struct.pack_into(">" + fmt, data, i, (v + delta) & ((1 << (8 * width)) - 1))


def mutate_data(r: RandGen, data: bytearray, min_len: int,
                max_len: int) -> bytes:
    """The 13-op byte/word-level buffer mutator."""
    data = bytearray(data)
    retry = True
    while retry or not r.one_of(3):
        retry = False
        op = r.intn(13)
        n = len(data)
        if op == 0:  # append byte
            if n >= max_len:
                retry = True
                continue
            data.append(r.rand(256))
        elif op == 1:  # remove byte
            if n == 0 or n <= min_len:
                retry = True
                continue
            del data[r.intn(n)]
        elif op == 2:  # replace byte
            if n == 0:
                retry = True
                continue
            data[r.intn(n)] = r.rand(256)
        elif op == 3:  # flip bit
            if n == 0:
                retry = True
                continue
            data[r.intn(n)] ^= 1 << r.intn(8)
        elif op == 4:  # swap two bytes
            if n < 2:
                retry = True
                continue
            i1, i2 = r.intn(n), r.intn(n)
            data[i1], data[i2] = data[i2], data[i1]
        elif op == 5:  # add/sub byte
            if n == 0:
                retry = True
                continue
            i = r.intn(n)
            delta = r.rand(2 * MAX_INC + 1) - MAX_INC or 1
            data[i] = (data[i] + delta) & 0xFF
        elif op in (6, 7, 8):  # add/sub u16/u32/u64 (either endianness)
            width = {6: 2, 7: 4, 8: 8}[op]
            if n < width:
                retry = True
                continue
            i = r.intn(n - width + 1)
            delta = r.rand(2 * MAX_INC + 1) - MAX_INC or 1
            if r.bin():
                fmt = {2: "H", 4: "I", 8: "Q"}[width]
                _ple(fmt, data, i, _le(fmt, data, i) + delta)
            else:
                _be_add(data, i, width, delta)
        elif op == 9:  # set byte to interesting value
            if n == 0:
                retry = True
                continue
            data[r.intn(n)] = r.rand_int() & 0xFF
        elif op in (10, 11, 12):  # set u16/u32/u64 to interesting value
            width = {10: 2, 11: 4, 12: 8}[op]
            if n < width:
                retry = True
                continue
            i = r.intn(n - width + 1)
            fmt = {2: "H", 4: "I", 8: "Q"}[width]
            v = r.rand_int() & ((1 << (8 * width)) - 1)
            if r.bin():
                v = int.from_bytes(v.to_bytes(width, "little"), "big")
            _ple(fmt, data, i, v)
    return bytes(data)


def mutation_args(target, c: Call) -> Tuple[List[Arg], List[Optional[Arg]]]:
    """Args eligible for mutation + their base pointer args."""
    args: List[Arg] = []
    bases: List[Optional[Arg]] = []

    def visit(arg: Arg, base: Optional[Arg]):
        t = arg.typ
        if isinstance(t, StructType):
            if target.special_structs.get(t.name) is None:
                return  # only individual fields are mutated
        elif isinstance(t, ArrayType):
            if t.kind == ArrayKind.RANGE_LEN and t.range_begin == t.range_end:
                return
        elif isinstance(t, (LenType, CsumType, ConstType)):
            return
        elif isinstance(t, BufferType):
            if t.kind == BufferKind.STRING and len(t.values) == 1:
                return  # string const
        if t.dir == Dir.OUT:
            return
        if base is not None and isinstance(base.typ.elem, StructType) and \
                target.special_structs.get(base.typ.elem.name) is not None:
            return  # special structs mutate as a whole
        args.append(arg)
        bases.append(base)

    foreach_arg(c, visit)
    return args, bases


# Operator indices shared with the device mix (ops/mutation._OP_MIX) and
# the attribution ledger: the host arg mutator splits into value
# (scalar/ptr/resource args) vs data (buffer bytes) to line up with the
# device's separate value/data kernels.  Imported, not redefined — the
# attribution module owns the index space (it is dependency-free), so a
# reorder there cannot silently miscredit host provenance here.
from ..telemetry.attribution import (  # noqa: E402
    OP_DATA,
    OP_INSERT,
    OP_REMOVE,
    OP_SPLICE,
    OP_VALUE,
)


def mutate(p: Prog, rng_or_seed, ncalls: int, ct=None,
           corpus=None) -> List[int]:
    """Mutate program p in place.  Returns the operator indices applied
    (OP_* above, one entry per successful mutation arm, in order) so
    callers can attribute eventual corpus yield to the operators that
    produced the mutant."""
    r = rng_or_seed if isinstance(rng_or_seed, RandGen) \
        else RandGen(p.target, seed=rng_or_seed)
    target = p.target
    corpus = corpus or []
    applied: List[int] = []

    retry = True
    stop = False
    while retry or not stop:
        if not retry:
            stop = r.one_of(3)
            if stop:
                break
        retry = False
        if r.n_out_of(1, 100):
            # splice with a random corpus program
            if not corpus or not p.calls:
                retry = True
                continue
            p0c = corpus[r.intn(len(corpus))].clone()
            idx = r.intn(len(p.calls))
            p.calls[idx:idx] = p0c.calls
            while len(p.calls) > ncalls:
                p.remove_call(len(p.calls) - 1)
            applied.append(OP_SPLICE)
        elif r.n_out_of(20, 31):
            # insert a new call, biased toward the tail
            if len(p.calls) >= ncalls:
                retry = True
                continue
            idx = r.biased_rand(len(p.calls) + 1, 5)
            c = p.calls[idx] if idx < len(p.calls) else None
            s = analyze(ct, p, c)
            calls = r.generate_call(s, p)
            p.insert_before(c, calls)
            applied.append(OP_INSERT)
        elif r.n_out_of(10, 11):
            # mutate args of a random call
            if not p.calls:
                retry = True
                continue
            c = p.calls[r.intn(len(p.calls))]
            if not c.args:
                retry = True
                continue
            if c.meta is target.mmap_syscall and r.n_out_of(99, 100):
                retry = True
                continue
            s = analyze(ct, p, c)
            updated = False
            while True:
                args, bases = mutation_args(target, c)
                if not args:
                    retry = not updated
                    break
                idx = r.intn(len(args))
                arg, base = args[idx], bases[idx]
                base_size = 0
                if base is not None and base.res is not None:
                    base_size = base.res.size()
                _mutate_arg(r, s, p, c, arg)
                applied.append(OP_DATA if isinstance(arg.typ, BufferType)
                               else OP_VALUE)
                updated = True
                if base is not None and base.res is not None and \
                        base_size < base.res.size():
                    na, calls1 = r.addr(s, base.typ, base.res.size(), base.res)
                    for c1 in calls1:
                        target.sanitize_call(c1)
                    p.insert_before(c, calls1)
                    base.page_index = na.page_index
                    base.page_offset = na.page_offset
                    base.pages_num = na.pages_num
                assign_sizes_call(target, c)
                if r.one_of(3):
                    break
        else:
            # remove a random call
            if not p.calls:
                retry = True
                continue
            p.remove_call(r.intn(len(p.calls)))
            applied.append(OP_REMOVE)

    for c in p.calls:
        target.sanitize_call(c)
    return applied


def _mutate_arg(r: RandGen, s: State, p: Prog, c: Call, arg: Arg) -> None:
    t = arg.typ
    target = p.target
    if isinstance(t, (IntType, FlagsType)):
        if r.bin():
            arg1, calls1 = r.generate_arg(s, t)
            p.replace_arg(c, arg, arg1, calls1)
        else:
            if r.n_out_of(1, 3):
                arg.val = (arg.val + r.intn(4) + 1) & UINT64_MAX
            elif r.n_out_of(1, 2):
                arg.val = (arg.val - r.intn(4) - 1) & UINT64_MAX
            else:
                arg.val ^= 1 << r.intn(64)
    elif isinstance(t, (ResourceType, VmaType, ProcType)):
        arg1, calls1 = r.generate_arg(s, t)
        p.replace_arg(c, arg, arg1, calls1)
    elif isinstance(t, BufferType):
        if t.kind in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE):
            min_len, max_len = 0, UINT64_MAX
            if t.kind == BufferKind.BLOB_RANGE:
                min_len, max_len = t.range_begin, t.range_end
            arg.data = mutate_data(r, bytearray(arg.data), min_len, max_len)
        elif t.kind == BufferKind.STRING:
            if r.bin():
                min_len, max_len = 0, UINT64_MAX
                if t.size != 0:
                    min_len = max_len = t.size
                arg.data = mutate_data(r, bytearray(arg.data), min_len, max_len)
            else:
                arg.data = r.rand_string(s, t.values, t.dir)
        elif t.kind == BufferKind.FILENAME:
            arg.data = r.filename(s)
        elif t.kind == BufferKind.TEXT:
            arg.data = r.mutate_text(t.text, arg.data)
    elif isinstance(t, ArrayType):
        count = len(arg.inner)
        if t.kind == ArrayKind.RAND_LEN:
            while count == len(arg.inner):
                count = r.rand_array_len()
        else:
            if t.range_begin == t.range_end:
                return
            while count == len(arg.inner):
                count = r.rand_range(t.range_begin, t.range_end)
        if count > len(arg.inner):
            calls: List[Call] = []
            while count > len(arg.inner):
                a1, calls1 = r.generate_arg(s, t.elem)
                arg.inner.append(a1)
                for c1 in calls1:
                    calls.append(c1)
                    s.analyze(c1)
            for c1 in calls:
                target.sanitize_call(c1)
            target.sanitize_call(c)
            p.insert_before(c, calls)
        else:
            for a1 in arg.inner[count:]:
                p.remove_arg(c, a1)
            del arg.inner[count:]
    elif isinstance(t, PtrType):
        if not isinstance(arg, PointerArg):
            return
        size = arg.res.size() if arg.res is not None else 1
        arg1, calls1 = r.addr(s, t, size, arg.res)
        p.replace_arg(c, arg, arg1, calls1)
    elif isinstance(t, StructType):
        gen = target.special_structs.get(t.name)
        if gen is None:
            raise TypeError("mutation_args returned a plain struct")
        arg1, calls1 = gen(r, s, t, arg)
        # Whole-struct replacement: after a serialize round-trip the old
        # fields are ConstArgs while the generator emits ResultArgs, so a
        # field-by-field replace would drop the res links (and leave the
        # chained clock_gettime dead).
        p.replace_arg(c, arg, arg1, calls1)
    elif isinstance(t, UnionType):
        options = [f for f in t.fields
                   if f.field_name != arg.option_type.field_name]
        if not options:
            return
        opt_t = options[r.intn(len(options))]
        p.remove_arg(c, arg.option)
        opt, calls = r.generate_arg(s, opt_t)
        arg1 = UnionArg(t, opt, opt_t)
        p.replace_arg(c, arg, arg1, calls)
    else:
        raise TypeError(f"cannot mutate arg of type {t}")


# ---------------------------------------------------------------------- #
# Minimization


def minimize(p0: Prog, call_index0: int,
             pred: Callable[[Prog, int], bool],
             crash: bool = False) -> Tuple[Prog, int]:
    """Iteratively simplify p0 while pred keeps holding."""
    target = p0.target
    name0 = p0.calls[call_index0].meta.name if call_index0 != -1 else ""

    # 1. glue all mmaps into one uber-mmap
    s = analyze(None, p0, None)
    mapped = [i for i, m in enumerate(s.pages) if m]
    if mapped and target.mmap_syscall is not None:
        lo, hi = mapped[0], mapped[-1]
        p = p0.clone()
        ci = call_index0
        i = 0
        while i < len(p.calls):
            if i != ci and p.calls[i].meta is target.mmap_syscall:
                p.remove_call(i)
                if i < ci:
                    ci -= 1
            else:
                i += 1
        p.calls.insert(0, target.make_mmap(lo, hi - lo + 1))
        if ci != -1:
            ci += 1
        if pred(p, ci):
            p0, call_index0 = p, ci

    # 2. drop calls back-to-front
    i = len(p0.calls) - 1
    while i >= 0:
        if i != call_index0:
            ci = call_index0 - 1 if i < call_index0 else call_index0
            p = p0.clone()
            p.remove_call(i)
            if pred(p, ci):
                p0, call_index0 = p, ci
        i -= 1

    # 3. per-arg simplification
    tried: set = set()

    def rec(p: Prog, call: Call, arg: Arg, path: str) -> bool:
        path += f"-{arg.typ.field_name}"
        t = arg.typ
        if isinstance(t, StructType):
            return any(rec(p, call, a, path) for a in arg.inner)
        if isinstance(t, UnionType):
            return rec(p, call, arg.option, path)
        if isinstance(t, PtrType):
            if isinstance(arg, PointerArg) and arg.res is not None:
                return rec(p, call, arg.res, path)
            return False
        if isinstance(t, ArrayType):
            for i, inner in enumerate(list(arg.inner)):
                ipath = f"{path}-{i}"
                if ipath not in tried and not crash:
                    can = (t.kind == ArrayKind.RANGE_LEN
                           and len(arg.inner) > t.range_begin) or \
                          t.kind == ArrayKind.RAND_LEN
                    if can:
                        arg.inner.remove(inner)
                        p.remove_arg(call, inner)
                        assign_sizes_call(target, call)
                        nonlocal p0
                        if pred(p, call_index0):
                            p0 = p
                        else:
                            tried.add(ipath)
                        return True
                if rec(p, call, inner, ipath):
                    return True
            return False
        if isinstance(t, (IntType, FlagsType, ProcType)):
            if crash or path in tried:
                return False
            tried.add(path)
            if arg.val == t.default():
                return False
            v0 = arg.val
            arg.val = t.default()
            if pred(p, call_index0):
                p0 = p
                return True
            arg.val = v0
            return False
        if isinstance(t, ResourceType):
            if crash or path in tried:
                return False
            tried.add(path)
            if arg.res is None:
                return False
            r0 = arg.res
            r0.uses.discard(arg)
            arg.res, arg.val = None, t.default()
            if pred(p, call_index0):
                p0 = p
                return True
            arg.res, arg.val = r0, 0
            r0.uses.add(arg)
            return False
        if isinstance(t, BufferType):
            if path in tried:
                return False
            tried.add(path)
            if t.kind not in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE):
                return False
            min_len = t.range_begin
            step = len(arg.data) - min_len
            while len(arg.data) > min_len and step > 0:
                if len(arg.data) - step >= min_len:
                    saved = arg.data
                    arg.data = arg.data[: len(arg.data) - step]
                    assign_sizes_call(target, call)
                    if pred(p, call_index0):
                        continue
                    arg.data = saved
                    assign_sizes_call(target, call)
                step //= 2
                if crash:
                    break
            p0 = p
            return False
        return False

    i = 0
    while i < len(p0.calls):
        tried = set()
        while True:
            p = p0.clone()
            call = p.calls[i]
            if not any(rec(p, call, a, str(j))
                       for j, a in enumerate(list(call.args))):
                break
        i += 1

    if call_index0 != -1:
        if call_index0 >= len(p0.calls) or \
                p0.calls[call_index0].meta.name != name0:
            raise RuntimeError("bad call index after minimization")
    return p0, call_index0


def trim_after(p: Prog, idx: int) -> None:
    """Drop all calls after idx, unlinking dataflow edges."""
    for i in range(len(p.calls) - 1, idx, -1):
        c = p.calls[i]

        def unlink(arg: Arg, _b):
            if isinstance(arg, ResultArg) and arg.res is not None:
                arg.res.uses.discard(arg)

        foreach_arg(c, unlink)
    del p.calls[idx + 1:]
