"""Typed syscall model: the type lattice that drives generation and mutation.

Capability parity with the reference type system (reference:
/root/reference/prog/types.go:10-340) — resources, consts, ints, flags,
lens, procs, checksums, vmas, buffers (blob/string/filename/text), arrays,
pointers, structs/unions, bitfields, endianness — but expressed as frozen
Python dataclasses that compile down to flat numpy tables
(`syzkaller_tpu.descriptions.tables`) which the JAX kernels index, instead
of being walked as trees on the hot path.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Optional, Tuple

UINT64_MAX = (1 << 64) - 1


class Dir(enum.IntEnum):
    IN = 0
    OUT = 1
    INOUT = 2


class IntKind(enum.IntEnum):
    PLAIN = 0
    FILEOFF = 1  # offset within a file
    RANGE = 2


class BufferKind(enum.IntEnum):
    BLOB_RAND = 0
    BLOB_RANGE = 1
    STRING = 2
    FILENAME = 3
    TEXT = 4  # machine code


class TextKind(enum.IntEnum):
    X86_REAL = 0
    X86_16 = 1
    X86_32 = 2
    X86_64 = 3
    ARM64 = 4


class ArrayKind(enum.IntEnum):
    RAND_LEN = 0
    RANGE_LEN = 1


class CsumKind(enum.IntEnum):
    INET = 0
    PSEUDO = 1


@dataclass(frozen=True)
class Type:
    """Common base. ``size == 0`` means variable-length."""

    name: str = ""
    field_name: str = ""
    size: int = 0
    dir: Dir = Dir.IN
    optional: bool = False

    @property
    def is_varlen(self) -> bool:
        return self.size == 0

    def default(self) -> int:
        return 0

    # Bitfield interface; only int-like types override.
    @property
    def bitfield_offset(self) -> int:
        return 0

    @property
    def bitfield_length(self) -> int:
        return 0

    @property
    def bitfield_middle(self) -> bool:
        """True for all but the last bitfield in a group (occupies 0 bytes)."""
        return False

    def with_dir(self, d: Dir) -> "Type":
        return replace(self, dir=d)

    def with_field(self, fname: str) -> "Type":
        return replace(self, field_name=fname)


@dataclass(frozen=True)
class IntCommon(Type):
    bitfield_off: int = 0
    bitfield_len: int = 0
    big_endian: bool = False
    bitfield_mdl: bool = False

    @property
    def bitfield_offset(self) -> int:
        return self.bitfield_off

    @property
    def bitfield_length(self) -> int:
        return self.bitfield_len

    @property
    def bitfield_middle(self) -> bool:
        return self.bitfield_mdl


@dataclass(frozen=True)
class ResourceDesc:
    name: str
    typ: "Type" = None  # underlying int type
    kind: Tuple[str, ...] = ()  # compatibility chain, most-general first
    values: Tuple[int, ...] = (0,)  # special (reset) values


@dataclass(frozen=True)
class ResourceType(Type):
    desc: ResourceDesc = None

    def default(self) -> int:
        return self.desc.values[0]

    @property
    def special_values(self) -> Tuple[int, ...]:
        return self.desc.values


@dataclass(frozen=True)
class ConstType(IntCommon):
    val: int = 0
    is_pad: bool = False

    def default(self) -> int:
        return self.val


@dataclass(frozen=True)
class IntType(IntCommon):
    kind: IntKind = IntKind.PLAIN
    range_begin: int = 0
    range_end: int = 0


@dataclass(frozen=True)
class FlagsType(IntCommon):
    vals: Tuple[int, ...] = ()


@dataclass(frozen=True)
class LenType(IntCommon):
    buf: str = ""  # name of the sized sibling field
    byte_size: int = 0  # 0: count elements; N: size in N-byte units


@dataclass(frozen=True)
class ProcType(IntCommon):
    """Per-process disjoint value ranges (ids that must not collide across
    parallel executor processes)."""

    values_start: int = 0
    values_per_proc: int = 1

    def default(self) -> int:
        return self.values_start


@dataclass(frozen=True)
class CsumType(IntCommon):
    kind: CsumKind = CsumKind.INET
    buf: str = ""
    protocol: int = 0  # for PSEUDO


@dataclass(frozen=True)
class VmaType(Type):
    range_begin: int = 0  # in pages
    range_end: int = 0


@dataclass(frozen=True)
class BufferType(Type):
    kind: BufferKind = BufferKind.BLOB_RAND
    range_begin: int = 0
    range_end: int = 0
    text: TextKind = TextKind.X86_64
    sub_kind: str = ""
    values: Tuple[str, ...] = ()  # possible values for STRING kind


@dataclass(frozen=True)
class ArrayType(Type):
    elem: Type = None
    kind: ArrayKind = ArrayKind.RAND_LEN
    range_begin: int = 0
    range_end: int = 0


@dataclass(frozen=True)
class PtrType(Type):
    elem: Type = None


@dataclass(frozen=True)
class StructType(Type):
    fields: Tuple[Type, ...] = ()
    align_attr: int = 0
    packed: bool = False


@dataclass(frozen=True)
class UnionType(Type):
    fields: Tuple[Type, ...] = ()


@dataclass(frozen=True)
class Syscall:
    id: int  # dense index into Target.syscalls
    nr: int  # kernel syscall number
    name: str  # full variant name, e.g. "open$generic"
    call_name: str  # base name, e.g. "open"
    args: Tuple[Type, ...] = ()
    ret: Optional[Type] = None


def is_pad(t: Type) -> bool:
    return isinstance(t, ConstType) and t.is_pad


def foreach_type(call: Syscall, fn) -> None:
    """Visit every type reachable from a syscall signature, pruning cycles
    through struct/union names (descriptions may be recursive via pointers)."""
    seen = set()

    def rec(t: Type):
        fn(t)
        if isinstance(t, (PtrType, ArrayType)):
            rec(t.elem)
        elif isinstance(t, (StructType, UnionType)):
            key = (t.name, t.dir, type(t).__name__)
            if key in seen:
                return
            seen.add(key)
            for f in t.fields:
                rec(f)

    for a in call.args:
        rec(a)
    if call.ret is not None:
        rec(call.ret)
