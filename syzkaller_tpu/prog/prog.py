"""Host-side program IR: a typed tree of calls and args.

This is the *boundary* representation — used to serialize programs for the
executor, to parse/persist the corpus, and for minimization. The fuzzing hot
path does not walk these trees; it operates on the fixed-width tensor encoding
in `syzkaller_tpu.prog.tensor` (batched on TPU). Capability parity with
reference /root/reference/prog/prog.go:10-382 (arg kinds, cross-call result
dataflow with use-edges, tree surgery).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .types import (
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    Type,
    UINT64_MAX,
    UnionType,
    VmaType,
)


def _swap(value: int, size: int) -> int:
    return int.from_bytes(value.to_bytes(size, "little"), "big")


def encode_value(value: int, size: int, big_endian: bool) -> int:
    value &= UINT64_MAX
    if not big_endian:
        return value
    if size not in (2, 4, 8):
        raise ValueError(f"bad size {size} for big-endian value")
    return _swap(value & ((1 << (8 * size)) - 1), size)


class Arg:
    """Base of the argument tree."""

    __slots__ = ("typ",)

    def __init__(self, typ: Type):
        self.typ = typ

    def size(self) -> int:
        return self.typ.size


class ConstArg(Arg):
    """Value of an int-like type (const/int/flags/len/proc/csum)."""

    __slots__ = ("val",)

    def __init__(self, typ: Type, val: int):
        super().__init__(typ)
        self.val = val & UINT64_MAX

    def value(self, pid: int = 0) -> int:
        """Wire value: endianness- and executor-pid-adjusted."""
        t = self.typ
        if isinstance(t, CsumType):
            return 0  # computed dynamically by the executor
        if isinstance(t, ProcType):
            v = t.values_start + t.values_per_proc * pid + self.val
            return encode_value(v, t.size, t.big_endian)
        if isinstance(t, ResourceType):
            base = t.desc.typ
            return encode_value(self.val, base.size, getattr(base, "big_endian", False))
        big = getattr(t, "big_endian", False)
        return encode_value(self.val, t.size, big)


class PointerArg(Arg):
    """Pointer in abstract page+offset form (used for PtrType and VmaType)."""

    __slots__ = ("page_index", "page_offset", "pages_num", "res")

    def __init__(self, typ: Type, page_index: int = 0, page_offset: int = 0,
                 pages_num: int = 0, res: Optional[Arg] = None):
        super().__init__(typ)
        self.page_index = page_index
        self.page_offset = page_offset  # may be negative: offset from page end
        self.pages_num = pages_num
        self.res = res  # pointee


class DataArg(Arg):
    """Byte payload of a BufferType."""

    __slots__ = ("data",)

    def __init__(self, typ: Type, data: bytes = b""):
        super().__init__(typ)
        self.data = bytes(data)

    def size(self) -> int:
        return len(self.data)


class GroupArg(Arg):
    """Struct or array contents."""

    __slots__ = ("inner",)

    def __init__(self, typ: Type, inner: Optional[List[Arg]] = None):
        super().__init__(typ)
        self.inner: List[Arg] = inner if inner is not None else []

    def size(self) -> int:
        t = self.typ
        if not t.is_varlen:
            return t.size
        if isinstance(t, StructType):
            sz = sum(f.size() for f in self.inner if not f.typ.bitfield_middle)
            if t.align_attr and sz % t.align_attr:
                sz += t.align_attr - sz % t.align_attr
            return sz
        if isinstance(t, ArrayType):
            return sum(e.size() for e in self.inner)
        raise TypeError(f"bad group arg type {t}")


class UnionArg(Arg):
    __slots__ = ("option", "option_type")

    def __init__(self, typ: Type, option: Arg, option_type: Type):
        super().__init__(typ)
        self.option = option
        self.option_type = option_type

    def size(self) -> int:
        if not self.typ.is_varlen:
            return self.typ.size
        return self.option.size()


class ResultArg(Arg):
    """Resource value: either a constant or a reference to a producing arg
    (cross-call dataflow). `uses` is the reverse edge set."""

    __slots__ = ("res", "op_div", "op_add", "val", "uses")

    def __init__(self, typ: Type, res: Optional[Arg] = None, val: int = 0,
                 op_div: int = 0, op_add: int = 0):
        super().__init__(typ)
        self.res = res
        self.op_div = op_div
        self.op_add = op_add
        self.val = val & UINT64_MAX
        self.uses: set = set()


class ReturnArg(Arg):
    """Denotes the syscall return value slot."""

    __slots__ = ("uses",)

    def __init__(self, typ: Optional[Type]):
        super().__init__(typ)
        self.uses: set = set()

    def size(self) -> int:
        raise RuntimeError("size() of a return arg")


def make_result_arg(typ: Type, res: Optional[Arg], val: int = 0) -> ResultArg:
    arg = ResultArg(typ, res=res, val=val)
    if res is not None:
        assert isinstance(res, (ResultArg, ReturnArg))
        res.uses.add(arg)
    return arg


def default_arg(t: Type) -> Arg:
    """The canonical simplest value of a type (used by minimization and to
    patch dangling result references)."""
    if isinstance(t, (IntType, ConstType, FlagsType, LenType, ProcType, CsumType)):
        return ConstArg(t, t.default())
    if isinstance(t, ResourceType):
        return make_result_arg(t, None, t.desc.typ.default())
    if isinstance(t, BufferType):
        # Fixed-size buffers must occupy their static size or sibling field
        # offsets diverge from the compiled layout.
        data = b"\x00" * t.size if t.size != 0 else b""
        return DataArg(t, data)
    if isinstance(t, ArrayType):
        return GroupArg(t, [])
    if isinstance(t, StructType):
        return GroupArg(t, [default_arg(f) for f in t.fields])
    if isinstance(t, UnionType):
        return UnionArg(t, default_arg(t.fields[0]), t.fields[0])
    if isinstance(t, VmaType):
        return PointerArg(t, 0, 0, 1, None)
    if isinstance(t, PtrType):
        res = None
        if not t.optional and t.dir != Dir.OUT:
            res = default_arg(t.elem)
        return PointerArg(t, 0, 0, 0, res)
    raise TypeError(f"unknown type {t}")


def inner_arg(arg: Arg) -> Optional[Arg]:
    """Dereference pointer args down to the pointee."""
    if isinstance(arg.typ, PtrType):
        if isinstance(arg, PointerArg):
            if arg.res is None:
                return None
            return inner_arg(arg.res)
        return None
    return arg


@dataclass
class Call:
    meta: Syscall
    args: List[Arg] = field(default_factory=list)
    ret: Optional[ReturnArg] = None


def foreach_subarg(arg: Arg, fn: Callable[[Arg, Optional[Arg]], None],
                   base: Optional[Arg] = None) -> None:
    """Depth-first traversal of an arg subtree. `fn(arg, base)` where base is
    the innermost enclosing pointer arg (None at top level)."""
    fn(arg, base)
    if isinstance(arg, GroupArg):
        for a in list(arg.inner):
            foreach_subarg(a, fn, base)
    elif isinstance(arg, PointerArg):
        if arg.res is not None:
            foreach_subarg(arg.res, fn, arg)
    elif isinstance(arg, UnionArg):
        foreach_subarg(arg.option, fn, base)


def foreach_arg(call: Call, fn: Callable[[Arg, Optional[Arg]], None]) -> None:
    for a in list(call.args):
        foreach_subarg(a, fn)


def foreach_subarg_offset(arg: Arg, fn: Callable[[Arg, int], None],
                          enter: Optional[Callable[[Arg, int], None]] = None,
                          leave: Optional[Callable[[Arg], None]] = None) -> None:
    """Traverse a pointee subtree with byte offsets of each sub-arg from the
    start of `arg` (mirrors copyin layout; reference prog/analysis.go).

    `enter`/`leave` fire around group/union containers so callers that need
    the ancestor chain (prog/checksum.py) share this one layout authority
    instead of re-implementing the offset rules."""

    def rec(a: Arg, offset: int) -> int:
        fn(a, offset)
        if isinstance(a, GroupArg):
            if enter is not None:
                enter(a, offset)
            if isinstance(a.typ, StructType):
                for f in a.inner:
                    rec(f, offset)
                    if not f.typ.bitfield_middle:
                        offset += f.size()
                # note: trailing align padding is part of struct size only
            else:  # array
                for e in a.inner:
                    offset = rec(e, offset)
            if leave is not None:
                leave(a)
            return offset
        if isinstance(a, UnionArg):
            if enter is not None:
                enter(a, offset)
            rec(a.option, offset)
            if leave is not None:
                leave(a)
            return offset + a.size()
        if isinstance(a, ReturnArg):
            return offset
        return offset + a.size()

    rec(arg, 0)


class Prog:
    """A syscall program: an ordered list of calls with cross-call dataflow."""

    def __init__(self, target, calls: Optional[List[Call]] = None):
        self.target = target
        self.calls: List[Call] = calls if calls is not None else []

    # ---- tree surgery (used by mutation/minimize on the host side) ----

    def insert_before(self, c: Optional[Call], calls: List[Call]) -> None:
        if not calls:
            return
        idx = len(self.calls)
        if c is not None:
            for i, cc in enumerate(self.calls):
                if cc is c:
                    idx = i
                    break
        self.calls[idx:idx] = calls

    def replace_arg(self, c: Call, arg: Arg, arg1: Arg, calls: List[Call]) -> None:
        for cc in calls:
            self.target.sanitize_call(cc)
        self.insert_before(c, calls)
        if isinstance(arg, ConstArg):
            arg.val = arg1.val
            arg.typ = arg1.typ
            # a ResultArg replacement registered itself as a user of its
            # source; it never enters the tree, so sever that edge
            if isinstance(arg1, ResultArg) and arg1.res is not None:
                arg1.res.uses.discard(arg1)
        elif isinstance(arg, ResultArg):
            if arg.res is not None:
                arg.res.uses.discard(arg)
            if isinstance(arg1, ResultArg):
                arg.res, arg.op_div, arg.op_add, arg.val = (
                    arg1.res, arg1.op_div, arg1.op_add, arg1.val)
                if arg.res is not None:
                    arg.res.uses.discard(arg1)
                    arg.res.uses.add(arg)
            else:
                # scalar replacement — e.g. re-generating an int field the
                # special-struct generator had produced as a ResultArg
                # (timespec nested inside itimerspec)
                arg.res, arg.op_div, arg.op_add = None, 0, 0
                arg.val = getattr(arg1, "val", 0)
            arg.typ = arg1.typ
        elif isinstance(arg, PointerArg):
            arg.page_index = arg1.page_index
            arg.page_offset = arg1.page_offset
            arg.pages_num = arg1.pages_num
            arg.res = arg1.res
            arg.typ = arg1.typ
        elif isinstance(arg, UnionArg):
            arg.option = arg1.option
            arg.option_type = arg1.option_type
        elif isinstance(arg, GroupArg):
            # Wholesale field replacement (special-struct regeneration):
            # field classes may differ between old and new (a deserialized
            # struct has ConstArg fields, the generator emits ResultArgs),
            # so sever the old subtree's dataflow and adopt the new fields.
            for f in arg.inner:
                self.remove_arg(c, f)
            arg.inner = arg1.inner
            arg.typ = arg1.typ
        elif isinstance(arg, DataArg):
            arg.data = arg1.data
        else:
            raise TypeError(f"replace_arg: bad arg kind {arg}")
        self.target.sanitize_call(c)

    def _owning_call(self, arg: Arg) -> Optional[Call]:
        for c in self.calls:
            found = [False]

            def chk(a: Arg, _b):
                if a is arg:
                    found[0] = True

            for top in c.args:
                foreach_subarg(top, chk)
            if c.ret is arg:
                found[0] = True
            if found[0]:
                return c
        return None

    def remove_arg(self, c: Call, arg0: Optional[Arg]) -> None:
        """Remove all dataflow edges to/from arg0's subtree; dangling consumers
        are rewritten to default constant resources."""
        if arg0 is None:
            return

        def visit(arg: Arg, _base):
            if isinstance(arg, ResultArg) and arg.res is not None:
                arg.res.uses.discard(arg)
            if isinstance(arg, (ResultArg, ReturnArg)):
                for user in list(arg.uses):
                    repl = make_result_arg(user.typ, None, user.typ.default())
                    # The dangling consumer lives in a *later* call, not in
                    # the call being removed — re-sanitize that call.
                    uc = self._owning_call(user) or c
                    self.replace_arg(uc, user, repl, [])

        foreach_subarg(arg0, visit)

    def remove_call(self, idx: int) -> None:
        c = self.calls.pop(idx)
        for arg in c.args:
            self.remove_arg(c, arg)
        self.remove_arg(c, c.ret)

    def clone(self) -> "Prog":
        """Deep copy preserving result-arg links."""
        mapping: dict = {}

        def copy_arg(arg: Optional[Arg]) -> Optional[Arg]:
            if arg is None:
                return None
            if isinstance(arg, ConstArg):
                new = ConstArg(arg.typ, arg.val)
            elif isinstance(arg, PointerArg):
                new = PointerArg(arg.typ, arg.page_index, arg.page_offset,
                                 arg.pages_num, copy_arg(arg.res))
            elif isinstance(arg, DataArg):
                new = DataArg(arg.typ, arg.data)
            elif isinstance(arg, GroupArg):
                new = GroupArg(arg.typ, [copy_arg(a) for a in arg.inner])
            elif isinstance(arg, UnionArg):
                new = UnionArg(arg.typ, copy_arg(arg.option), arg.option_type)
            elif isinstance(arg, ResultArg):
                res = mapping.get(id(arg.res)) if arg.res is not None else None
                new = ResultArg(arg.typ, res=res, val=arg.val,
                                op_div=arg.op_div, op_add=arg.op_add)
                if res is not None:
                    res.uses.add(new)
            elif isinstance(arg, ReturnArg):
                new = ReturnArg(arg.typ)
            else:
                raise TypeError(f"clone: bad arg {arg}")
            mapping[id(arg)] = new
            return new

        calls = []
        for c in self.calls:
            nc = Call(meta=c.meta, args=[copy_arg(a) for a in c.args],
                      ret=copy_arg(c.ret))
            calls.append(nc)
        return Prog(self.target, calls)

    def validate(self) -> None:
        """Structural invariants: use-edges symmetric, result refs point to
        args of earlier-or-same calls."""
        seen: set = set()
        for c in self.calls:
            for a in c.args:
                foreach_subarg(a, lambda arg, _b: seen.add(id(arg)))
            if c.ret is not None:
                seen.add(id(c.ret))
        for c in self.calls:
            def check(arg: Arg, _base):
                if isinstance(arg, ResultArg) and arg.res is not None:
                    if id(arg.res) not in seen:
                        raise AssertionError(
                            f"result arg references a detached arg in {c.meta.name}")
                    if arg not in arg.res.uses:
                        raise AssertionError("use edge missing")
                if isinstance(arg, (ResultArg, ReturnArg)):
                    for u in arg.uses:
                        if u.res is not arg:
                            raise AssertionError("reverse use edge broken")
            for a in c.args:
                foreach_subarg(a, check)
            if c.ret is not None:
                check(c.ret, None)
