"""Checksum dependency computation at exec-serialization time.

Plays the role of the reference's prog/checksum.go (calcChecksumsCall,
reference prog/checksum.go:29-160): csum-typed fields are generated and
copied in as zero, and each one yields an extra exec instruction telling
the executor how to compute the real value *after* all copyins land —
a list of (data-range | constant) chunks summed with the ones'-complement
internet checksum and stored back into the field.

Chunk semantics:
- ``csum[BUF, inet, intN]`` — one data chunk covering BUF's bytes, where
  BUF is a sibling field of the csum field or the literal name ``parent``
  for the enclosing struct (whose own csum field is zero during the sum,
  which is exactly the IP-header convention).
- ``csum[BUF, pseudo, PROTO, intN]`` — the TCP/UDP pseudo-header: data
  chunks for the ``src_ip``/``dst_ip`` fields of the nearest enclosing
  struct that has both (IPv4 or IPv6 shapes both work since sizes come
  from the fields), constant chunks for PROTO and BUF's byte length, then
  BUF's data chunk.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from .prog import Arg, GroupArg, foreach_subarg_offset
from .types import CsumKind, CsumType, StructType

CHUNK_DATA = 0
CHUNK_CONST = 1


@dataclass
class Chunk:
    kind: int      # CHUNK_DATA | CHUNK_CONST
    value: int     # offset from pointee base (DATA) or be16 value (CONST)
    size: int      # bytes covered (DATA) or const width (CONST)


@dataclass
class CsumInstr:
    offset: int    # byte offset of the csum field from the pointee base
    size: int      # width of the csum field
    chunks: List[Chunk]


def _find_csums(pointee: Arg) -> List[Tuple[Arg, int, list]]:
    """Collect (csum_arg, offset, ancestor_stack) using the one layout
    authority, foreach_subarg_offset's enter/leave hooks."""
    stack: list = []
    out: List[Tuple[Arg, int, list]] = []

    def fn(a: Arg, off: int) -> None:
        if isinstance(getattr(a, "typ", None), CsumType):
            out.append((a, off, list(stack)))

    foreach_subarg_offset(
        pointee, fn,
        enter=lambda a, off: stack.append((a, off)),
        leave=lambda a: stack.pop())
    return out


def _find_field(group: GroupArg, base: int, name: str, deep: bool = False) \
        -> Optional[Tuple[Arg, int]]:
    """Find a field by name in a struct; with deep=True also look one level
    into nested struct fields (an IPv4 header struct inside the packet)."""
    if not isinstance(group.typ, StructType):
        return None
    off = base
    for f in group.inner:
        if f.typ.field_name == name:
            return f, off
        if deep and isinstance(f, GroupArg) and isinstance(f.typ, StructType):
            sub = _find_field(f, off, name)
            if sub is not None:
                return sub
        if not f.typ.bitfield_middle:
            off += f.size()
    return None


def calc_checksums(pointee: Arg) -> List[CsumInstr]:
    """Compute csum instructions for one copied-in pointee tree.

    Offsets are relative to the pointee base; the exec serializer adds the
    physical address.  Unresolvable references (no such sibling, no
    enclosing src_ip/dst_ip) degrade to no instruction — the field just
    stays zero, matching the reference's leniency for partially-formed
    mutants.
    """
    found = _find_csums(pointee)
    out: List[CsumInstr] = []
    for arg, off, stack in found:
        typ: CsumType = arg.typ
        groups = [(g, goff) for g, goff in stack if isinstance(g, GroupArg)]
        if not groups:
            continue
        # Resolve BUF: "parent" = enclosing struct; else nearest ancestor
        # struct owning a field of that name.
        target: Optional[Tuple[Arg, int]] = None
        if typ.buf == "parent":
            target = groups[-1]
        else:
            for g, goff in reversed(groups):
                target = _find_field(g, goff, typ.buf)
                if target is not None:
                    break
        if target is None:
            continue
        buf_arg, buf_off = target
        chunks: List[Chunk] = []
        if typ.kind == CsumKind.PSEUDO:
            # The IP addresses may sit directly in an ancestor (IPv6
            # packet shape) or inside its nested header struct (IPv4
            # shape) — search one level deep.
            src = dst = None
            for g, goff in reversed(groups):
                src = _find_field(g, goff, "src_ip", deep=True)
                dst = _find_field(g, goff, "dst_ip", deep=True)
                if src is not None and dst is not None:
                    break
                src = dst = None
            if src is None or dst is None:
                continue
            chunks.append(Chunk(CHUNK_DATA, src[1], src[0].size()))
            chunks.append(Chunk(CHUNK_DATA, dst[1], dst[0].size()))
            # IPv6 pseudo headers (16-byte addresses) carry 32-bit
            # upper-layer length and next-header words; IPv4's are 16-bit
            # (reference prog/checksum.go composePseudoCsumIPv4/IPv6).
            # The 4-byte form also keeps payloads >= 64KiB from silently
            # truncating the length term.
            cw = 4 if src[0].size() == 16 else 2
            chunks.append(Chunk(CHUNK_CONST, typ.protocol, cw))
            chunks.append(Chunk(CHUNK_CONST, buf_arg.size(), cw))
        chunks.append(Chunk(CHUNK_DATA, buf_off, buf_arg.size()))
        out.append(CsumInstr(offset=off, size=arg.size(), chunks=chunks))
    return out


def ip_checksum(data: bytes, extra: int = 0) -> int:
    """Host-side reference of the executor's computation (for csource and
    tests): ones'-complement sum of big-endian 16-bit words."""
    acc = extra
    if len(data) % 2:
        data = data + b"\x00"
    for i in range(0, len(data), 2):
        acc += (data[i] << 8) | data[i + 1]
    while acc >> 16:
        acc = (acc & 0xFFFF) + (acc >> 16)
    return (~acc) & 0xFFFF
