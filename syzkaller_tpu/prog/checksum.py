"""Checksum dependency computation at exec-serialization time.

Plays the role of the reference's prog/checksum.go (calcChecksumsCall,
reference prog/checksum.go:29-160): csum-typed fields are generated and
copied in as zero, and each one yields an extra exec instruction telling
the executor how to compute the real value *after* all copyins land —
a list of (data-range | constant) chunks summed with the ones'-complement
internet checksum and stored back into the field.

Chunk semantics:
- ``csum[BUF, inet, intN]`` — one data chunk covering BUF's bytes, where
  BUF is a sibling field of the csum field or the literal name ``parent``
  for the enclosing struct (whose own csum field is zero during the sum,
  which is exactly the IP-header convention).
- ``csum[BUF, pseudo, PROTO, intN]`` — the TCP/UDP pseudo-header: data
  chunks for the ``src_ip``/``dst_ip`` fields of the nearest enclosing
  struct that has both (IPv4 or IPv6 shapes both work since sizes come
  from the fields), constant chunks for PROTO and BUF's byte length, then
  BUF's data chunk.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

from .prog import Arg, GroupArg, ReturnArg, UnionArg
from .types import CsumKind, CsumType, StructType

CHUNK_DATA = 0
CHUNK_CONST = 1


@dataclass
class Chunk:
    kind: int      # CHUNK_DATA | CHUNK_CONST
    value: int     # offset from pointee base (DATA) or be16 value (CONST)
    size: int      # bytes covered (DATA) or const width (CONST)


@dataclass
class CsumInstr:
    offset: int    # byte offset of the csum field from the pointee base
    size: int      # width of the csum field
    chunks: List[Chunk]


def _walk(arg: Arg, offset: int, stack, out) -> int:
    """Mirror of foreach_subarg_offset (prog.py:254-278) that also records
    the ancestor group stack for each visited arg.  The return value must
    advance exactly like foreach_subarg_offset's rec() — struct and array
    groups return the accumulated field offset (no trailing align padding),
    since that is where the copyins actually placed the bytes."""
    if isinstance(arg, GroupArg):
        stack.append((arg, offset))
        off = offset
        if isinstance(arg.typ, StructType):
            for f in arg.inner:
                _walk(f, off, stack, out)
                if not f.typ.bitfield_middle:
                    off += f.size()
        else:
            for e in arg.inner:
                off = _walk(e, off, stack, out)
        stack.pop()
        return off
    if isinstance(arg, UnionArg):
        stack.append((arg, offset))
        _walk(arg.option, offset, stack, out)
        stack.pop()
        return offset + arg.size()
    if isinstance(arg, ReturnArg):
        return offset
    if isinstance(arg.typ, CsumType):
        out.append((arg, offset, list(stack)))
    return offset + arg.size()


def _find_field(group: GroupArg, base: int, name: str) \
        -> Optional[Tuple[Arg, int]]:
    if not isinstance(group.typ, StructType):
        return None
    off = base
    for f in group.inner:
        if f.typ.field_name == name:
            return f, off
        if not f.typ.bitfield_middle:
            off += f.size()
    return None


def calc_checksums(pointee: Arg) -> List[CsumInstr]:
    """Compute csum instructions for one copied-in pointee tree.

    Offsets are relative to the pointee base; the exec serializer adds the
    physical address.  Unresolvable references (no such sibling, no
    enclosing src_ip/dst_ip) degrade to no instruction — the field just
    stays zero, matching the reference's leniency for partially-formed
    mutants.
    """
    found: List[Tuple[Arg, int, list]] = []
    _walk(pointee, 0, [], found)
    out: List[CsumInstr] = []
    for arg, off, stack in found:
        typ: CsumType = arg.typ
        groups = [(g, goff) for g, goff in stack if isinstance(g, GroupArg)]
        if not groups:
            continue
        # Resolve BUF: "parent" = enclosing struct; else nearest ancestor
        # struct owning a field of that name.
        target: Optional[Tuple[Arg, int]] = None
        if typ.buf == "parent":
            target = groups[-1]
        else:
            for g, goff in reversed(groups):
                target = _find_field(g, goff, typ.buf)
                if target is not None:
                    break
        if target is None:
            continue
        buf_arg, buf_off = target
        chunks: List[Chunk] = []
        if typ.kind == CsumKind.PSEUDO:
            src = dst = None
            for g, goff in reversed(groups):
                src = _find_field(g, goff, "src_ip")
                dst = _find_field(g, goff, "dst_ip")
                if src is not None and dst is not None:
                    break
                src = dst = None
            if src is None or dst is None:
                continue
            chunks.append(Chunk(CHUNK_DATA, src[1], src[0].size()))
            chunks.append(Chunk(CHUNK_DATA, dst[1], dst[0].size()))
            chunks.append(Chunk(CHUNK_CONST, typ.protocol, 2))
            chunks.append(Chunk(CHUNK_CONST, buf_arg.size(), 2))
        chunks.append(Chunk(CHUNK_DATA, buf_off, buf_arg.size()))
        out.append(CsumInstr(offset=off, size=arg.size(), chunks=chunks))
    return out


def ip_checksum(data: bytes, extra: int = 0) -> int:
    """Host-side reference of the executor's computation (for csource and
    tests): ones'-complement sum of big-endian 16-bit words."""
    acc = extra
    if len(data) % 2:
        data = data + b"\x00"
    for i in range(0, len(data), 2):
        acc += (data[i] << 8) | data[i + 1]
    while acc >> 16:
        acc = (acc & 0xFFFF) + (acc >> 16)
    return (~acc) & 0xFFFF
