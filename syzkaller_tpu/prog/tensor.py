"""Fixed-width tensor encoding of programs (the device representation).

A batch of programs is three arrays (struct-of-arrays, jit/vmap friendly):

    call_id  [B, C]      i32   syscall id per call slot, -1 = empty
    slot_val [B, C, S]   u64   per template slot: value / producer call
                               index (REF) / payload length (DATA) /
                               page count (VMA); PTR and LEN slots are
                               fully determined by the static template
    data     [B, C, D]   u8    per-call copyin arena image (byte payloads)

Everything else — which slots exist, their kinds/types/offsets, block
layout, addresses — is static per syscall id and lives in the compiled
tables (descriptions/tables.py). The encoder assigns each call one page of
the data area and prepends a single uber-mmap, mirroring the reference
minimizer's mmap normalization (reference: prog/mutation.go:274-310, and
the exec-format physical addressing of prog/encodingexec.go:202-214).

REF sentinel: REF_NONE means "no producer" -> the type's default value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..descriptions.tables import (
    SK_DATA,
    SK_LEN,
    SK_PTR,
    SK_REF,
    SK_VALUE,
    SK_VMA,
    CompiledTables,
    MAX_SLOTS_PER_CALL,
)
from .analysis import assign_sizes_call
from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    ReturnArg,
    UnionArg,
    default_arg,
    make_result_arg,
)
from .types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    UINT64_MAX,
    UnionType,
    VmaType,
    is_pad,
)

VMA_MAX_PAGES = 64  # symmetric encode/decode clamp for SK_VMA slots

REF_NONE = UINT64_MAX


@dataclass
class TensorFormat:
    max_calls: int = 16
    max_slots: int = 16
    arena: int = 320  # bytes per call, 8-aligned

    @classmethod
    def for_tables(cls, tables: CompiledTables, max_calls: int = 16):
        return cls(
            max_calls=max_calls,
            max_slots=max(int(tables.max_slots), 1),
            arena=(max(int(tables.max_arena), 8) + 7) & ~7,
        )


@dataclass
class ProgBatch:
    """Host-side (numpy) batch; device code treats it as a pytree of arrays."""

    call_id: np.ndarray   # [B, C] int32
    slot_val: np.ndarray  # [B, C, S] uint64
    data: np.ndarray      # [B, C, D] uint8

    @property
    def batch(self) -> int:
        return self.call_id.shape[0]

    @classmethod
    def empty(cls, fmt: TensorFormat, batch: int) -> "ProgBatch":
        return cls(
            call_id=np.full((batch, fmt.max_calls), -1, dtype=np.int32),
            slot_val=np.zeros((batch, fmt.max_calls, fmt.max_slots),
                              dtype=np.uint64),
            data=np.zeros((batch, fmt.max_calls, fmt.arena), dtype=np.uint8),
        )


# ---------------------------------------------------------------------- #
# Template-shaped tree construction + slot-order walking.
# The walk order here MUST mirror descriptions/tables.py::flatten; the
# correspondence is pinned by tests/test_tensor.py::test_walk_matches_tables.


def template_count(t: ArrayType) -> int:
    if t.kind == ArrayKind.RANGE_LEN:
        return max(t.range_begin, 1)
    return 1


def template_arg(t, _budget: Optional[List[int]] = None) -> Arg:
    """Default arg tree with exactly the template's shape.

    Iterative (explicit work stack) and slot-budgeted: self-referential
    types (linked lists etc.) would otherwise expand forever.  Mirrors
    descriptions/tables.flatten, which stops emitting slots at
    MAX_SLOTS_PER_CALL — once the budget is spent, pointer expansion is
    pruned (res=None, the canonical &nil).  Cycles always pass through a
    pointer, and every pointer consumes a budget unit before its pointee
    expands, so the tree is finite; the non-pointer shape below a cut is
    still built in full so decoded programs keep valid struct/union/array
    arity.  Budget accounting runs in the same DFS preorder as flatten
    and walk_slots, so the walked slot kinds stay pinned to the tables."""
    budget = _budget if _budget is not None else [MAX_SLOTS_PER_CALL]
    out: List[Arg] = []
    # stack of (type, put) where put() places the constructed Arg into its
    # parent; children are pushed reversed so pops run left-to-right
    stack: List[Tuple[object, object]] = [(t, out.append)]
    while stack:
        typ, put = stack.pop()
        if isinstance(typ, PtrType):
            arg = PointerArg(typ, 0, 0, 0, None)
            put(arg)
            budget[0] -= 1
            if budget[0] > 0:
                def _set_res(a, _p=arg):
                    _p.res = a

                stack.append((typ.elem, _set_res))
        elif isinstance(typ, VmaType):
            budget[0] -= 1
            put(PointerArg(typ, 0, 0, max(1, typ.range_begin), None))
        elif isinstance(typ, ArrayType):
            g = GroupArg(typ, [])
            put(g)
            stack.extend((typ.elem, g.inner.append)
                         for _ in range(template_count(typ)))
        elif isinstance(typ, StructType):
            g = GroupArg(typ, [])
            put(g)
            stack.extend((f, g.inner.append) for f in reversed(typ.fields))
        elif isinstance(typ, UnionType):
            u = UnionArg(typ, None, typ.fields[0])
            put(u)

            def _set_opt(a, _u=u):
                _u.option = a

            stack.append((typ.fields[0], _set_opt))
        elif isinstance(typ, BufferType):
            budget[0] -= 1
            put(DataArg(typ, b""))
        elif isinstance(typ, ResourceType):
            budget[0] -= 1
            put(make_result_arg(typ, None, typ.default()))
        else:
            if not (isinstance(typ, ConstType) and is_pad(typ)):
                budget[0] -= 1
            put(default_arg(typ))
    return out[0]


def walk_slots(args: List[Arg], budget: Optional[List[int]] = None
               ) -> Iterator[Tuple[Arg, int]]:
    """Yield (arg, slot_kind) in template order over a template-shaped tree."""
    if budget is None:
        budget = [MAX_SLOTS_PER_CALL]

    def rec(arg: Arg):
        if budget[0] <= 0:
            return
        t = arg.typ
        if isinstance(t, ResourceType):
            budget[0] -= 1
            yield arg, (SK_REF if t.dir == Dir.IN else SK_VALUE)
        elif isinstance(t, (LenType, CsumType)):
            # Both are recomputed, never mutated: sizes by
            # assign_sizes_call, checksums by the executor at run time
            # (a device-proposed csum value would poison the inet sum,
            # whose buf range includes the field itself as zero).
            budget[0] -= 1
            yield arg, SK_LEN
        elif isinstance(t, (IntType, FlagsType, ProcType)):
            budget[0] -= 1
            yield arg, SK_VALUE
        elif isinstance(t, ConstType):
            if not is_pad(t):
                budget[0] -= 1
                yield arg, SK_VALUE
        elif isinstance(t, VmaType):
            budget[0] -= 1
            yield arg, SK_VMA
        elif isinstance(t, BufferType):
            budget[0] -= 1
            yield arg, SK_DATA
        elif isinstance(t, PtrType):
            budget[0] -= 1
            yield arg, SK_PTR
            if isinstance(arg, PointerArg) and arg.res is not None:
                yield from rec(arg.res)
        elif isinstance(t, StructType):
            for f in arg.inner:
                yield from rec(f)
        elif isinstance(t, UnionType):
            yield from rec(arg.option)
        elif isinstance(t, ArrayType):
            for e in arg.inner:
                yield from rec(e)

    for a in args:
        yield from rec(a)


def _zip_template(meta, actual_args: List[Arg]) -> List[Arg]:
    """Build a template-shaped tree taking values from the actual tree where
    shapes align (lossy projection of a host program onto the template).
    Slot-budgeted like template_arg: pointer expansion is pruned once the
    per-arg budget is spent, so self-referential types terminate."""
    budget = [MAX_SLOTS_PER_CALL]

    def proj(t, a: Optional[Arg]) -> Arg:
        if isinstance(t, PtrType):
            budget[0] -= 1
            if budget[0] <= 0:
                return PointerArg(t, 0, 0, 0, None)
            res = None
            if isinstance(a, PointerArg):
                res = a.res
            return PointerArg(t, 0, 0, 0, proj(t.elem, res))
        if isinstance(t, VmaType):
            budget[0] -= 1
            npg = a.pages_num if isinstance(a, PointerArg) and a.pages_num \
                else max(1, t.range_begin)
            return PointerArg(t, 0, 0, npg, None)
        if isinstance(t, ArrayType):
            n = template_count(t)
            actual = a.inner if isinstance(a, GroupArg) else []
            return GroupArg(t, [
                proj(t.elem, actual[i] if i < len(actual) else None)
                for i in range(n)])
        if isinstance(t, StructType):
            actual = a.inner if isinstance(a, GroupArg) else []
            return GroupArg(t, [
                proj(f, actual[i] if i < len(actual) else None)
                for i, f in enumerate(t.fields)])
        if isinstance(t, UnionType):
            # template pins option 0
            opt0 = t.fields[0]
            if isinstance(a, UnionArg) and \
                    a.option_type.field_name == opt0.field_name:
                return UnionArg(t, proj(opt0, a.option), opt0)
            return UnionArg(t, proj(opt0, None), opt0)
        if isinstance(t, BufferType):
            budget[0] -= 1
            data = a.data if isinstance(a, DataArg) else b""
            return DataArg(t, data)
        if isinstance(t, ResourceType):
            budget[0] -= 1
            if isinstance(a, ResultArg):
                na = ResultArg(t, res=a.res, val=a.val, op_div=a.op_div,
                               op_add=a.op_add)
                return na
            return ResultArg(t, None, t.default())
        if isinstance(t, (IntType, FlagsType, ProcType, LenType, CsumType,
                          ConstType)):
            if not (isinstance(t, ConstType) and is_pad(t)):
                budget[0] -= 1
            val = a.val if isinstance(a, ConstArg) else t.default()
            return ConstArg(t, val)
        return template_arg(t, budget)

    return [proj(t, actual_args[i] if i < len(actual_args) else None)
            for i, t in enumerate(meta.args)]


# ---------------------------------------------------------------------- #
# Encode: Prog -> tensor row


def _producer_index(p: Prog, res: Arg, limit: int) -> int:
    """Index of the call that produces `res`, or -1."""
    for i, c in enumerate(p.calls[:limit]):
        if c.ret is res:
            return i
        found = [False]

        def chk(a: Arg, _b):
            if a is res:
                found[0] = True

        from .prog import foreach_subarg
        for a in c.args:
            foreach_subarg(a, chk)
        if found[0]:
            return i
    return -1


def encode_prog(tables: CompiledTables, fmt: TensorFormat, p: Prog,
                out: Optional[ProgBatch] = None, row: int = 0) -> ProgBatch:
    if out is None:
        out = ProgBatch.empty(fmt, 1)
    call_id = out.call_id[row]
    slot_val = out.slot_val[row]
    data = out.data[row]
    call_id[:] = -1
    slot_val[:] = 0
    data[:] = 0

    # skip synthesized mmap preludes: the tensor form re-adds its own
    calls = [c for c in p.calls if c.meta is not p.target.mmap_syscall]

    for ci, c in enumerate(calls[: fmt.max_calls]):
        call_id[ci] = c.meta.id
        proj = _zip_template(c.meta, c.args)
        off = tables.call_slot_off[c.meta.id]
        for si, (arg, kind) in enumerate(walk_slots(proj)):
            if si >= fmt.max_slots:
                break
            gk = int(tables.slot_kind[off + si]) if si < int(
                tables.call_slot_cnt[c.meta.id]) else kind
            if kind == SK_VALUE:
                slot_val[ci, si] = np.uint64(arg.val & UINT64_MAX) \
                    if isinstance(arg, ConstArg) else np.uint64(
                        getattr(arg, "val", 0) & UINT64_MAX)
            elif kind == SK_REF:
                idx = -1
                if isinstance(arg, ResultArg) and arg.res is not None:
                    idx = _producer_index(p, arg.res, len(p.calls))
                    if idx >= 0:
                        # renumber into the mmap-stripped window
                        orig = p.calls[idx]
                        idx = calls.index(orig) if orig in calls else -1
                if 0 <= idx < fmt.max_calls:
                    slot_val[ci, si] = np.uint64(idx)
                else:
                    slot_val[ci, si] = np.uint64(REF_NONE)
            elif kind == SK_DATA:
                cap = int(tables.slot_size[off + si]) \
                    if si < int(tables.call_slot_cnt[c.meta.id]) else 0
                payload = arg.data[:cap] if isinstance(arg, DataArg) else b""
                slot_val[ci, si] = np.uint64(len(payload))
                blk = int(tables.slot_block[off + si])
                if blk >= 0 and payload:
                    base = int(tables.block_addr[
                        int(tables.call_block_off[c.meta.id]) + blk]) + \
                        int(tables.slot_offset[off + si])
                    end = min(base + len(payload), fmt.arena)
                    if base < fmt.arena:
                        data[ci, base:end] = np.frombuffer(
                            payload[: end - base], dtype=np.uint8)
            elif kind == SK_VMA:
                npg = arg.pages_num if isinstance(arg, PointerArg) else 1
                slot_val[ci, si] = np.uint64(
                    max(1, min(npg, VMA_MAX_PAGES)))
            # SK_PTR / SK_LEN: static / recomputed
    return out


# ---------------------------------------------------------------------- #
# Decode: tensor row -> Prog


def decode_prog(tables: CompiledTables, fmt: TensorFormat,
                batch: ProgBatch, row: int = 0) -> Prog:
    target = tables.target
    call_id = batch.call_id[row]
    slot_val = batch.slot_val[row]
    data = batch.data[row]

    prog = Prog(target, [])
    page_cursor = 1  # page 0 reserved
    vma_cursor = fmt.max_calls + 1  # vma pages allocated after call arenas
    decoded: List[Call] = []

    for ci in range(fmt.max_calls):
        cid = int(call_id[ci])
        if cid < 0:
            continue
        meta = target.syscalls[cid]
        args = [template_arg(t) for t in meta.args]
        call = Call(meta=meta, args=args,
                    ret=ReturnArg(meta.ret) if meta.ret is not None
                    else ReturnArg(None))
        off = int(tables.call_slot_off[cid])
        cnt = int(tables.call_slot_cnt[cid])
        call_page = page_cursor
        page_cursor += 1
        bo = int(tables.call_block_off[cid])

        for si, (arg, kind) in enumerate(walk_slots(args)):
            if si >= min(cnt, fmt.max_slots):
                break
            v = int(slot_val[ci, si])
            if kind == SK_VALUE:
                if isinstance(arg, ConstArg):
                    arg.val = v & UINT64_MAX
                elif isinstance(arg, ResultArg):
                    arg.val = v & UINT64_MAX
            elif kind == SK_REF:
                if v != REF_NONE and v < len(decoded):
                    src_call = decoded[int(v)]
                    src = _find_source(src_call, arg.typ, target)
                    if src is not None:
                        arg.res = src
                        arg.val = 0
                        src.uses.add(arg)
            elif kind == SK_DATA:
                cap = int(tables.slot_size[off + si])
                n = min(v, cap)
                blk = int(tables.slot_block[off + si])
                if blk >= 0:
                    base = int(tables.block_addr[bo + blk]) + \
                        int(tables.slot_offset[off + si])
                    arg.data = bytes(data[ci, base:base + n].tobytes())
                else:
                    arg.data = b"\x00" * n
            elif kind == SK_VMA:
                arg.pages_num = max(1, min(v, VMA_MAX_PAGES))
                arg.page_index = vma_cursor
                vma_cursor += int(arg.pages_num)
            elif kind == SK_PTR:
                blk = int(tables.slot_target_block[off + si])
                if isinstance(arg, PointerArg) and blk >= 0:
                    arg.page_index = call_page
                    arg.page_offset = int(tables.block_addr[bo + blk])

        assign_sizes_call(target, call)
        target.sanitize_call(call)
        decoded.append(call)
        prog.calls.append(call)

    # uber-mmap covering call arenas + vma region
    if target.mmap_syscall is not None and prog.calls:
        prog.calls.insert(0, target.make_mmap(0, max(vma_cursor, page_cursor)))
    return prog


def _find_source(call: Call, res_type, target) -> Optional[Arg]:
    """A resource source inside `call` compatible with res_type.

    Falls back to root-kind compatibility (kind[0] match) when no
    prefix-compatible source exists: generation's rare cross-kind resource
    reuse (create_resource's 1/1000 any-kind path, mirroring the
    reference's prog/rand.go resourceCentric trick) produces such refs, and
    decode must accept whatever encode preserved."""
    want = res_type.desc.name
    root = res_type.desc.kind[0]

    def ok(desc) -> int:
        if target.is_compatible_resource(want, desc.name):
            return 2
        return 1 if desc.kind[0] == root else 0

    best: Optional[Arg] = None
    best_rank = 0
    if call.ret is not None and isinstance(call.ret.typ, ResourceType):
        best_rank = ok(call.ret.typ.desc)
        if best_rank == 2:
            return call.ret
        best = call.ret if best_rank else None

    found: List[Arg] = []

    from .prog import foreach_subarg

    def chk(a: Arg, _b):
        nonlocal best, best_rank
        if found:
            return
        if isinstance(a, ResultArg) and isinstance(a.typ, ResourceType) \
                and a.typ.dir != Dir.IN:
            rank = ok(a.typ.desc)
            if rank == 2:
                found.append(a)
            elif rank > best_rank:
                best, best_rank = a, rank

    for a in call.args:
        foreach_subarg(a, chk)
    if found:
        return found[0]
    return best


def encode_batch(tables: CompiledTables, fmt: TensorFormat,
                 progs: List[Prog]) -> ProgBatch:
    out = ProgBatch.empty(fmt, len(progs))
    for i, p in enumerate(progs):
        encode_prog(tables, fmt, p, out, i)
    return out


def decode_batch(tables: CompiledTables, fmt: TensorFormat,
                 batch: ProgBatch) -> List[Prog]:
    return [decode_prog(tables, fmt, batch, i) for i in range(batch.batch)]
