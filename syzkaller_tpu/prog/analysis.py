"""Conservative program state analysis + len-field assignment.

State replay tracks which resources/files/strings/pages are live at a point
in the program (drives generation and resource reuse); assign_sizes recomputes
LenType args after mutation. Capability parity with reference
/root/reference/prog/analysis.go:15-170 and /root/reference/prog/size.go.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    foreach_subarg,
    inner_arg,
)
from .types import (
    ArrayType,
    BufferKind,
    BufferType,
    Dir,
    LenType,
    ResourceType,
    StructType,
    VmaType,
    is_pad,
)


class State:
    """What is [potentially] live after executing a prefix of a program."""

    def __init__(self, target, ct=None):
        self.target = target
        self.ct = ct  # choice table (may be None)
        self.files: Dict[str, bool] = {}
        self.resources: Dict[str, List[Arg]] = {}
        self.strings: Dict[str, bool] = {}
        self.pages = [False] * target.num_pages

    def analyze(self, c: Call) -> None:
        def visit(arg: Arg, _base):
            t = arg.typ
            if isinstance(t, ResourceType):
                if t.dir != Dir.IN:
                    self.resources.setdefault(t.desc.name, []).append(arg)
            elif isinstance(t, BufferType) and isinstance(arg, DataArg):
                if t.dir != Dir.OUT and arg.data:
                    if t.kind == BufferKind.STRING:
                        self.strings[arg.data.decode("latin1")] = True
                    elif t.kind == BufferKind.FILENAME:
                        self.files[arg.data.decode("latin1")] = True

        for a in c.args:
            foreach_subarg(a, visit)
        if c.ret is not None:
            visit(c.ret, None)

        start, npages, mapped = self.target.analyze_mmap(c)
        if npages:
            end = min(start + npages, len(self.pages))
            for i in range(start, end):
                self.pages[i] = mapped


def analyze(ct, p: Prog, c: Optional[Call]) -> State:
    """State up to but not including call c (or the whole program)."""
    s = State(p.target, ct)
    for c1 in p.calls:
        if c1 is c:
            break
        s.analyze(c1)
    return s


# ---------------------------------------------------------------------- #
# Len-field assignment


def _generate_size(target, arg: Optional[Arg], len_type: LenType) -> int:
    if arg is None:
        return 0  # optional pointer
    t = arg.typ
    if isinstance(t, VmaType):
        return arg.pages_num * target.page_size
    if isinstance(t, ArrayType) and isinstance(arg, GroupArg):
        if len_type.byte_size:
            return arg.size() // len_type.byte_size
        return len(arg.inner)
    if len_type.byte_size:
        return arg.size() // len_type.byte_size
    return arg.size()


def _assign_sizes(target, args: List[Arg], parents: Dict[int, Arg]) -> None:
    by_field = {a.typ.field_name: a for a in args if not is_pad(a.typ)}
    for arg in args:
        arg = inner_arg(arg)
        if arg is None:
            continue
        t = arg.typ
        if not isinstance(t, LenType) or not isinstance(arg, ConstArg):
            continue
        buf = by_field.get(t.buf)
        if buf is not None:
            arg.val = _generate_size(target, inner_arg(buf), t)
            continue
        if t.buf == "parent":
            parent = parents.get(id(arg))
            if parent is not None:
                v = parent.size()
                arg.val = v // t.byte_size if t.byte_size else v
            continue
        # path to a named ancestor struct
        parent = parents.get(id(arg))
        assigned = False
        while parent is not None:
            if t.buf == parent.typ.name:
                v = parent.size()
                arg.val = v // t.byte_size if t.byte_size else v
                assigned = True
                break
            parent = parents.get(id(parent))
        if not assigned:
            raise ValueError(
                f"len field {t.field_name!r} references unknown field {t.buf!r}")


def assign_sizes_call(target, c: Call) -> None:
    parents: Dict[int, Arg] = {}

    def collect(arg: Arg, _base):
        if isinstance(arg.typ, StructType) and isinstance(arg, GroupArg):
            for f in arg.inner:
                fi = inner_arg(f)
                if fi is not None:
                    parents[id(fi)] = arg

    for a in c.args:
        foreach_subarg(a, collect)

    _assign_sizes(target, c.args, parents)

    def fix_structs(arg: Arg, _base):
        if isinstance(arg.typ, StructType) and isinstance(arg, GroupArg):
            _assign_sizes(target, arg.inner, parents)

    for a in c.args:
        foreach_subarg(a, fix_structs)
