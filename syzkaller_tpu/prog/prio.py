"""Call-pair priorities + choice table (host reference implementation).

Semantics parity with reference /root/reference/prog/prio.go:27-247:
static priorities from shared resource/struct/filename usage, dynamic
priorities from corpus co-occurrence, normalization to [0.1, 1], and a
per-row cumulative-sum choice table sampled by binary search. The numpy
arrays produced here are exactly what the device sampler
(syzkaller_tpu.ops.prio) uploads — prefix sums + searchsorted are already
the array-friendly formulation.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence

import numpy as np

from .types import (
    ArrayType,
    BufferKind,
    BufferType,
    IntType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    UnionType,
    VmaType,
    foreach_type,
)


def calc_static_priorities(target) -> np.ndarray:
    n = len(target.syscalls)
    uses: Dict[str, Dict[int, float]] = {}

    for c in target.syscalls:
        def note(weight: float, ident: str, c=c):
            m = uses.setdefault(ident, {})
            if weight > m.get(c.id, 0.0):
                m[c.id] = weight

        def visit(t, c=c, note=note):
            if isinstance(t, ResourceType):
                if t.desc.name in ("pid", "uid", "gid"):
                    # auxiliary ids that appear in many structs
                    note(0.1, f"res{t.desc.name}")
                else:
                    ident = "res"
                    for i, k in enumerate(t.desc.kind):
                        ident += "-" + k
                        w = 1.0 if i == len(t.desc.kind) - 1 else 0.2
                        note(w, ident)
            elif isinstance(t, PtrType):
                if isinstance(t.elem, (StructType, UnionType)):
                    note(1.0, f"ptrto-{t.elem.name}")
                if isinstance(t.elem, ArrayType):
                    note(1.0, f"ptrto-{t.elem.elem.name}")
            elif isinstance(t, BufferType):
                if t.kind == BufferKind.STRING and t.sub_kind:
                    note(0.2, f"str-{t.sub_kind}")
                elif t.kind == BufferKind.FILENAME:
                    note(1.0, "filename")
            elif isinstance(t, VmaType):
                note(0.5, "vma")

        foreach_type(c, visit)

    prios = np.zeros((n, n), dtype=np.float32)
    for calls in uses.values():
        ids = list(calls.items())
        for c0, w0 in ids:
            for c1, w1 in ids:
                if c0 != c1:
                    prios[c0, c1] += w0 * w1
    # self-priority = max priority wrt others
    for c0 in range(n):
        prios[c0, c0] = prios[c0].max()
    normalize_prios(prios)
    return prios


def calc_dynamic_prio(target, corpus) -> np.ndarray:
    n = len(target.syscalls)
    prios = np.zeros((n, n), dtype=np.float32)
    mmap = target.mmap_syscall
    for p in corpus:
        ids = [c.meta.id for c in p.calls
               if mmap is None or c.meta is not mmap]
        for id0 in ids:
            for id1 in ids:
                if id0 != id1:
                    prios[id0, id1] += 1.0
    normalize_prios(prios)
    return prios


def normalize_prios(prios: np.ndarray) -> None:
    """Row-wise: zero entries get a small floor, then scale to [0.1, 1]."""
    for row in prios:
        mx = row.max()
        if mx == 0:
            row[:] = 1.0
            continue
        nz = row[row != 0]
        mn = nz.min()
        nzero = int((row == 0).sum())
        if nzero:
            mn = mn / (2 * nzero)
        row[row == 0] = mn
        if mx == mn:  # all-equal row: everything maps to the top of the range
            row[:] = 1.0
            continue
        np.clip((row - mn) / (mx - mn) * 0.9 + 0.1, None, 1.0, out=row)


def calculate_priorities(target, corpus) -> np.ndarray:
    """static ⊙ dynamic."""
    static = calc_static_priorities(target)
    dynamic = calc_dynamic_prio(target, corpus)
    return static * dynamic


class ChoiceTable:
    """Weighted next-call sampler: per-row integer prefix sums."""

    def __init__(self, target, prios: Optional[np.ndarray],
                 enabled: Optional[Sequence] = None):
        self.target = target
        if enabled is not None:
            # ids arrive from RPC/host-detection; Syscalls from local code
            calls = [target.syscalls[c] if isinstance(c, int) else c
                     for c in enabled]
        else:
            calls = list(target.syscalls)
        self.enabled_calls = calls
        self._enabled_ids = {c.id for c in calls}
        n = len(target.syscalls)
        if prios is None:
            prios = np.ones((n, n), dtype=np.float32)
        else:
            # RPC delivers prios as a JSON list-of-lists
            prios = np.asarray(prios, dtype=np.float32)
        mask = np.zeros(n, dtype=bool)
        mask[[c.id for c in calls]] = True
        weights = (prios * 1000).astype(np.int64) * mask[None, :]
        self.run = np.cumsum(weights, axis=1)
        self.run[~mask, :] = 0
        self.mask = mask

    def enabled(self, call_id: int) -> bool:
        return call_id in self._enabled_ids

    def choose(self, rng, bias_call: int = -1) -> int:
        if bias_call < 0 or not self.mask[bias_call]:
            return self.enabled_calls[rng.randrange(len(self.enabled_calls))].id
        row = self.run[bias_call]
        total = int(row[-1])
        if total == 0:
            return self.enabled_calls[rng.randrange(len(self.enabled_calls))].id
        while True:
            x = rng.randrange(total)
            i = int(np.searchsorted(row, x, side="right"))
            if self.mask[i]:
                return i


def build_choice_table(target, prios=None, enabled=None) -> ChoiceTable:
    return ChoiceTable(target, prios, enabled)
