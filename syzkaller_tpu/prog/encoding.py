"""Human-readable program serialization (corpus / logs / repro format).

Plays the role of the reference's text format (reference:
/root/reference/prog/encoding.go:16-580): round-trippable, one call per
line, resources named rN. The grammar is original to this framework:

    r0 = open(&0:0:1="./f\\x00", 0x0, 0x0)
    read(r0, &1:0:1, 0x10)
    pipe(&2:0:1={r1, r2})

  arg :=  0x<hex>                      integer value
        | rN [/0x<div>] [+0x<add>]     resource reference (or declaration
                                       when in an out-resource position)
        | &pg:off:npg=<arg> | &pg:off:npg | &nil    pointer [+ pointee]
        | &vma pg:npg                  vma address
        | "<escaped bytes>"            data buffer
        | {a, b, ...}                  struct/array
        | @field=<arg>                 union option
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    ReturnArg,
    UnionArg,
    default_arg,
    make_result_arg,
)
from .types import (
    ArrayType,
    BufferType,
    Dir,
    PtrType,
    ResourceType,
    StructType,
    UnionType,
    VmaType,
    is_pad,
)


class DeserializeError(Exception):
    pass


# ---------------------------------------------------------------------- #
# Serialization


def _escape(data: bytes) -> str:
    out = []
    for b in data:
        if 32 <= b < 127 and b not in (ord('"'), ord("\\")):
            out.append(chr(b))
        else:
            out.append(f"\\x{b:02x}")
    return "".join(out)


def serialize(p: Prog) -> str:
    names: Dict[int, str] = {}
    counter = [0]

    def name_for(arg: Arg) -> str:
        if id(arg) not in names:
            names[id(arg)] = f"r{counter[0]}"
            counter[0] += 1
        return names[id(arg)]

    def fmt(arg: Optional[Arg]) -> str:
        if arg is None:
            return "&nil"
        if isinstance(arg, ConstArg):
            return hex(arg.val)
        if isinstance(arg, ResultArg):
            ref = None
            if arg.res is not None:
                ref = names[id(arg.res)]
                if arg.op_div:
                    ref += f"/{hex(arg.op_div)}"
                if arg.op_add:
                    ref += f"+{hex(arg.op_add)}"
            if arg.uses:
                # this arg is itself a resource source: declare a name,
                # chained to its own reference (r5=r3) or constant value
                # (r5=0xffff..) so a round-trip preserves semantics
                decl = name_for(arg)
                if ref is None and arg.val != arg.typ.default():
                    ref = hex(arg.val)
                return f"{decl}={ref}" if ref is not None else decl
            return ref if ref is not None else hex(arg.val)
        if isinstance(arg, PointerArg):
            if isinstance(arg.typ, VmaType):
                return f"&vma {arg.page_index}:{arg.pages_num}"
            head = f"&{arg.page_index}:{arg.page_offset}:{arg.pages_num}"
            if arg.res is None:
                # canonical null pointer collapses to &nil; any other
                # pointee-less pointer keeps its address
                if (arg.page_index, arg.page_offset, arg.pages_num) == (0, 0, 0):
                    return "&nil"
                return head
            return f"{head}={fmt(arg.res)}"
        if isinstance(arg, DataArg):
            if arg.typ.dir == Dir.OUT:
                # out-buffer contents are kernel-written; only length matters
                return f"zero({hex(len(arg.data))})"
            return f'"{_escape(arg.data)}"'
        if isinstance(arg, GroupArg):
            inner = [fmt(a) for a in arg.inner if not is_pad(a.typ)]
            return "{" + ", ".join(inner) + "}"
        if isinstance(arg, UnionArg):
            return f"@{arg.option_type.field_name}={fmt(arg.option)}"
        raise TypeError(f"cannot serialize {arg}")

    lines = []
    for c in p.calls:
        body = f"{c.meta.name}({', '.join(fmt(a) for a in c.args)})"
        if c.ret is not None and c.ret.uses:
            body = f"{name_for(c.ret)} = {body}"
        lines.append(body)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------- #
# Deserialization

_TOK = re.compile(
    r"""\s*(?:
      (?P<str>"(?:\\x[0-9a-fA-F]{2}|[^"\\])*")
    | (?P<res>r\d+)
    | (?P<num>-?0x[0-9a-fA-F]+|-?\d+)
    | (?P<name>[a-zA-Z_][\w$]*)
    | (?P<punct>[=(){},:@&+/])
    )""",
    re.VERBOSE,
)


class _P:
    def __init__(self, line: str):
        self.toks: List[Tuple[str, str]] = []
        i = 0
        while i < len(line):
            m = _TOK.match(line, i)
            if not m:
                if line[i:].strip() == "":
                    break
                raise DeserializeError(f"bad token at {line[i:]!r}")
            i = m.end()
            self.toks.append((m.lastgroup, m.group(m.lastgroup)))
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def accept(self, kind, val=None):
        k, v = self.peek()
        if k == kind and (val is None or v == val):
            self.i += 1
            return v
        return None

    def expect(self, kind, val=None):
        v = self.accept(kind, val)
        if v is None:
            raise DeserializeError(
                f"expected {val or kind}, got {self.peek()[1]!r}")
        return v


def _unescape_str(s: str) -> bytes:
    s = s[1:-1]
    out = bytearray()
    i = 0
    while i < len(s):
        if s[i] == "\\" and i + 3 < len(s) + 1 and s[i + 1] == "x":
            out.append(int(s[i + 2:i + 4], 16))
            i += 4
        else:
            out.append(ord(s[i]))
            i += 1
    return bytes(out)


def _strip_comment(raw: str) -> str:
    """Cut at the first '#' that is outside a double-quoted string."""
    in_str = False
    i = 0
    while i < len(raw):
        ch = raw[i]
        if in_str:
            if ch == "\\":
                i += 1
            elif ch == '"':
                in_str = False
        elif ch == '"':
            in_str = True
        elif ch == "#":
            return raw[:i]
        i += 1
    return raw


def deserialize(target, text: str) -> Prog:
    p = Prog(target, [])
    bound: Dict[str, Arg] = {}

    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        lx = _P(line)
        first = lx.expect("name") if lx.peek()[0] == "name" else lx.expect("res")
        ret_name = None
        if lx.accept("punct", "="):
            ret_name = first
            call_name = lx.expect("name")
        else:
            call_name = first
        meta = target.syscall_map.get(call_name)
        if meta is None:
            raise DeserializeError(f"unknown syscall {call_name!r}")
        lx.expect("punct", "(")

        def parse_arg(t) -> Arg:
            k, v = lx.peek()
            if k == "num":
                lx.next()
                val = int(v, 0)
                if isinstance(t, ResourceType):
                    return make_result_arg(t, None, val)
                return ConstArg(t, val)
            if k == "res":
                lx.next()
                src = bound.get(v)
                if src is None:
                    # Unbound name. In an out/inout position this is a
                    # declaration of a new resource source; in a pure IN
                    # position the defining line was lost (corpus decay) —
                    # degrade to the default value without binding.
                    arg = make_result_arg(t, None, t.default())
                    if t.dir == Dir.IN:
                        return arg
                    bound[v] = arg
                    if lx.accept("punct", "="):
                        nk, nv = lx.peek()
                        if nk == "num":
                            lx.next()
                            arg.val = int(nv, 0) & ((1 << 64) - 1)
                            return arg
                        refname = lx.expect("res")
                        ref = bound.get(refname)
                        if ref is None:
                            raise DeserializeError(
                                f"declaration {v}={refname} references "
                                f"unbound {refname}")
                        if lx.accept("punct", "/"):
                            arg.op_div = int(lx.expect("num"), 0)
                        if lx.accept("punct", "+"):
                            arg.op_add = int(lx.expect("num"), 0)
                        arg.res = ref
                        arg.val = 0
                        ref.uses.add(arg)
                    return arg
                op_div = op_add = 0
                if lx.accept("punct", "/"):
                    op_div = int(lx.expect("num"), 0)
                if lx.accept("punct", "+"):
                    op_add = int(lx.expect("num"), 0)
                arg = make_result_arg(t, src, 0)
                arg.op_div, arg.op_add = op_div, op_add
                return arg
            if k == "str":
                lx.next()
                return DataArg(t, _unescape_str(v))
            if k == "name" and v == "zero":
                lx.next()
                lx.expect("punct", "(")
                n = int(lx.expect("num"), 0)
                lx.expect("punct", ")")
                return DataArg(t, b"\x00" * n)
            if k == "punct" and v == "&":
                lx.next()
                if lx.accept("name", "nil"):
                    return PointerArg(t, 0, 0, 0, None)
                if lx.accept("name", "vma"):
                    pg = int(lx.expect("num"), 0)
                    lx.expect("punct", ":")
                    npg = int(lx.expect("num"), 0)
                    return PointerArg(t, pg, 0, npg, None)
                pg = int(lx.expect("num"), 0)
                lx.expect("punct", ":")
                off = int(lx.expect("num"), 0)
                lx.expect("punct", ":")
                npg = int(lx.expect("num"), 0)
                res = None
                if lx.accept("punct", "="):
                    res = parse_arg(t.elem)
                return PointerArg(t, pg, off, npg, res)
            if k == "punct" and v == "{":
                lx.next()
                inner: List[Arg] = []
                if isinstance(t, StructType):
                    idx = 0
                    for f in t.fields:
                        if is_pad(f):
                            inner.append(default_arg(f))
                            continue
                        if idx > 0:
                            lx.expect("punct", ",")
                        idx += 1
                        inner.append(parse_arg(f))
                    lx.expect("punct", "}")
                    return GroupArg(t, inner)
                # array
                first_el = True
                while not lx.accept("punct", "}"):
                    if not first_el:
                        lx.expect("punct", ",")
                    first_el = False
                    inner.append(parse_arg(t.elem))
                return GroupArg(t, inner)
            if k == "punct" and v == "@":
                lx.next()
                fname = lx.expect("name")
                lx.expect("punct", "=")
                opt_t = next((f for f in t.fields if f.field_name == fname),
                             None)
                if opt_t is None:
                    raise DeserializeError(
                        f"union {t.name} has no option {fname!r}")
                return UnionArg(t, parse_arg(opt_t), opt_t)
            raise DeserializeError(f"cannot parse arg from {v!r}")

        args = []
        for i, at in enumerate(meta.args):
            if i > 0:
                lx.expect("punct", ",")
            args.append(parse_arg(at))
        lx.expect("punct", ")")

        ret = ReturnArg(meta.ret) if meta.ret is not None else ReturnArg(None)
        c = Call(meta=meta, args=args, ret=ret)
        if ret_name is not None:
            bound[ret_name] = ret
        p.calls.append(c)

    # Rebind: any name declared by a ReturnArg must link uses (they were
    # created with make_result_arg against the ReturnArg directly, so the
    # use-edges are already present).
    return p


def call_set(text: str) -> List[str]:
    """Names of calls mentioned in a serialized program (cheap, no target)."""
    out = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"(?:r\d+\s*=\s*)?([a-zA-Z_][\w$]*)\(", line)
        if m:
            out.append(m.group(1))
    return out
