"""Host-side program generation: the CPU reference implementation.

Semantics-parity with the reference's randomized generator (reference:
/root/reference/prog/rand.go:69-305,440-695 and prog/generation.go:12-31):
magnitude-biased ints with a special-values table, quadratic biased choice,
flag combination sampling, stateful filename/string pools, page-granular
address allocation that synthesizes mmap calls, and recursive resource
construction via ctor call sequences.

On the hot path the framework uses the vmapped device generator
(syzkaller_tpu.ops.generation); this module seeds corpora, regenerates the
long tail the device kernels don't model (special structs, text), and is the
baseline that bench.py compares against.
"""

from __future__ import annotations

import math
import random
from typing import List, Optional, Tuple

from .analysis import State, analyze, assign_sizes_call
from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    ReturnArg,
    UnionArg,
    default_arg,
    foreach_arg,
    make_result_arg,
)
from .types import (
    ArrayKind,
    ArrayType,
    BufferKind,
    BufferType,
    ConstType,
    CsumType,
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ProcType,
    PtrType,
    ResourceType,
    StructType,
    Syscall,
    UINT64_MAX,
    UnionType,
    VmaType,
)

SPECIAL_INTS = (
    0, 1, 31, 32, 63, 64, 127, 128, 129, 255, 256, 257, 511, 512,
    1023, 1024, 1025, 2047, 2048, 4095, 4096,
    (1 << 15) - 1, 1 << 15, (1 << 15) + 1,
    (1 << 16) - 1, 1 << 16, (1 << 16) + 1,
    (1 << 31) - 1, 1 << 31, (1 << 31) + 1,
    (1 << 32) - 1, 1 << 32, (1 << 32) + 1,
)

PUNCT = b"!@#$%^&*()-+\\/:.,-'[]{}"

# TextKind -> ifuzz mode (arm64 has no table: word-aligned random bytes)
from .types import TextKind as _TK
from ..ifuzz import MODE_LONG64 as _ML, MODE_PROT16 as _M16, \
    MODE_PROT32 as _M32, MODE_REAL16 as _MR

_TEXT_MODE = {_TK.X86_REAL: _MR, _TK.X86_16: _M16,
              _TK.X86_32: _M32, _TK.X86_64: _ML}



class RandGen:
    """Seeded random value engine for program generation/mutation."""

    def __init__(self, target, seed: Optional[int] = None,
                 rng: Optional[random.Random] = None):
        self.target = target
        self.rng = rng if rng is not None else random.Random(seed)
        self.in_create_resource = False
        self.rec_depth: dict = {}

    # --- primitive samplers ---

    def intn(self, n: int) -> int:
        return self.rng.randrange(n)

    def rand(self, n: int) -> int:
        return self.intn(n)

    def rand_range(self, begin: int, end: int) -> int:
        return begin + self.intn(end - begin + 1)

    def bin(self) -> bool:
        return self.intn(2) == 0

    def one_of(self, n: int) -> bool:
        return self.intn(n) == 0

    def n_out_of(self, n: int, out_of: int) -> bool:
        return self.intn(out_of) < n

    def rand64(self) -> int:
        return self.rng.getrandbits(64)

    def rand_int(self) -> int:
        """Magnitude-biased interesting integer."""
        v = self.rand64()
        if self.n_out_of(100, 182):
            v %= 10
        elif self.n_out_of(50, 82):
            v = SPECIAL_INTS[self.intn(len(SPECIAL_INTS))]
        elif self.n_out_of(10, 32):
            v %= 256
        elif self.n_out_of(10, 22):
            v %= 4 << 10
        elif self.n_out_of(10, 12):
            v %= 64 << 10
        else:
            v %= 1 << 31
        if self.n_out_of(100, 107):
            pass
        elif self.n_out_of(5, 7):
            v = (-v) & UINT64_MAX
        else:
            v = (v << self.intn(63)) & UINT64_MAX
        return v

    def rand_range_int(self, begin: int, end: int) -> int:
        if self.one_of(100):
            return self.rand_int()
        return begin + self.intn(end - begin + 1)

    def biased_rand(self, n: int, k: int) -> int:
        """Random int in [0, n); probability of n-1 is k times that of 0."""
        nf, kf = float(n), float(k)
        rf = nf * (kf / 2 + 1) * self.rng.random()
        bf = (-1 + math.sqrt(1 + 2 * kf * rf / nf)) * nf / kf
        return min(int(bf), n - 1)

    def rand_array_len(self) -> int:
        max_len = 10
        return (max_len - self.biased_rand(max_len + 1, 10) + 1) % (max_len + 1)

    def rand_buf_len(self) -> int:
        if self.n_out_of(50, 56):
            return self.rand(256)
        if self.n_out_of(5, 6):
            return 4 << 10
        return 0

    def rand_page_count(self) -> int:
        if self.n_out_of(100, 106):
            return self.rand(4) + 1
        if self.n_out_of(5, 6):
            return self.rand(20) + 1
        return (self.rand(3) + 1) * 1024

    def flags(self, vals: Tuple[int, ...]) -> int:
        if not vals:
            return self.rand64()
        if self.n_out_of(90, 111):
            v = 0
            while True:
                v |= vals[self.rand(len(vals))]
                if self.bin():
                    return v
        if self.n_out_of(10, 21):
            return vals[self.rand(len(vals))]
        if self.n_out_of(10, 11):
            return 0
        return self.rand64()

    def filename(self, s: State) -> bytes:
        dir_ = "."
        if self.one_of(2) and s.files:
            dir_ = self.rng.choice(sorted(s.files))
            if dir_.endswith("\x00"):
                dir_ = dir_[:-1]
        if not s.files or self.one_of(10):
            i = 0
            while True:
                f = f"{dir_}/file{i}\x00"
                if f not in s.files:
                    return f.encode("latin1")
                i += 1
        return self.rng.choice(sorted(s.files)).encode("latin1")

    def rand_string(self, s: State, values: Tuple[str, ...], dir: Dir) -> bytes:
        data = self._rand_string_impl(s, values)
        if dir == Dir.OUT:
            return b"\x00" * len(data)
        return data

    def _rand_string_impl(self, s: State, values: Tuple[str, ...]) -> bytes:
        if values:
            return self.rng.choice(values).encode("latin1")
        if s.strings and self.bin():
            return self.rng.choice(sorted(s.strings)).encode("latin1")
        buf = bytearray()
        while self.n_out_of(3, 4):
            if self.n_out_of(10, 21):
                d = self.target.string_dictionary
                if d:
                    buf += self.rng.choice(d).encode("latin1")
            elif self.n_out_of(10, 11):
                buf.append(PUNCT[self.intn(len(PUNCT))])
            else:
                buf.append(self.intn(256))
        if not self.one_of(100):
            buf.append(0)
        return bytes(buf)

    def generate_text(self, kind) -> bytes:
        """x86 machine code via the ifuzz table (reference
        prog/rand.go:373-404 generateText -> pkg/ifuzz); arm64 and unknown
        kinds fall back to word-aligned random bytes."""
        from ..ifuzz import Config, generate

        mode = _TEXT_MODE.get(kind)
        if mode is None:
            nwords = 4 + self.intn(12)
            return bytes(self.intn(256) for _ in range(4 * nwords))
        cfg = Config(length=2 + self.intn(15), mode=mode)
        return generate(cfg, self.rng)

    def mutate_text(self, kind, text: bytes) -> bytes:
        from ..ifuzz import Config, mutate

        mode = _TEXT_MODE.get(kind)
        if mode is None:
            from .mutation import mutate_data

            return mutate_data(self, bytearray(text), 40, 60)
        return mutate(Config(mode=mode), text, self.rng)

    # --- address allocation ---

    def _addr1(self, s: State, typ, size: int, data: Optional[Arg]):
        npages = max(1, (size + self.target.page_size - 1)
                     // self.target.page_size)
        if self.bin():
            return self.rand_page_addr(s, typ, npages, data, False), []
        max_pages = self.target.num_pages
        for i in range(max_pages - npages):
            if not any(s.pages[i:i + npages]):
                c = self.target.make_mmap(i, npages)
                return PointerArg(typ, i, 0, 0, data), [c]
        return self.rand_page_addr(s, typ, npages, data, False), []

    def alloc(self, s: State, typ, size: int, data: Optional[Arg]):
        """Guaranteed-valid allocation (reference prog.Gen.Alloc): for
        buffers the program itself must read back (e.g. clock_gettime
        output feeding a timespec), never the deliberately-corrupted
        offsets addr() mixes in."""
        return self._addr1(s, typ, size, data)

    def addr(self, s: State, typ, size: int, data: Optional[Arg]):
        arg, calls = self._addr1(s, typ, size, data)
        if self.n_out_of(50, 102):
            pass
        elif self.n_out_of(50, 52):
            arg.page_offset = -size
        elif self.n_out_of(1, 2):
            arg.page_offset = self.intn(self.target.page_size)
        elif size > 0:
            arg.page_offset = -self.intn(size)
        return arg, calls

    def rand_page_addr(self, s: State, typ, npages: int,
                       data: Optional[Arg], vma: bool) -> PointerArg:
        starts = [i for i in range(self.target.num_pages - npages)
                  if all(s.pages[i:i + npages])]
        if starts:
            page = starts[self.rand(len(starts))]
        else:
            page = self.rand(self.target.num_pages - npages)
        return PointerArg(typ, page, 0, npages if vma else 0, data)

    # --- resource construction ---

    def create_resource(self, s: State, res: ResourceType):
        if self.in_create_resource:
            special = res.special_values
            return make_result_arg(res, None, special[self.intn(len(special))]), []
        self.in_create_resource = True
        try:
            kind = res.desc.name
            if self.one_of(1000):
                all_kinds = [k for k in self.target.resource_map
                             if self.target.is_compatible_resource(
                                 res.desc.kind[0], k)]
                if all_kinds:
                    kind = self.rng.choice(sorted(all_kinds))
            metas = list(self.target.resource_ctors.get(kind, ()))
            if s.ct is not None:
                metas = [m for m in metas if s.ct.enabled(m.id)]
            if not metas:
                return make_result_arg(res, None, res.default()), []
            for _ in range(1000):
                meta = metas[self.intn(len(metas))]
                calls = self.generate_particular_call(s, meta)
                s1 = State(self.target, s.ct)
                s1.analyze(calls[-1])
                allres = []
                for kind1, res1 in sorted(s1.resources.items()):
                    if self.target.is_compatible_resource(kind, kind1):
                        allres.extend(res1)
                if allres:
                    return make_result_arg(
                        res, allres[self.intn(len(allres))], 0), calls
                # Unsuccessful: unlink and discard.
                for c in calls:
                    def unlink(arg, _b):
                        if isinstance(arg, ResultArg) and arg.res is not None:
                            arg.res.uses.discard(arg)
                    foreach_arg(c, unlink)
            raise RuntimeError(f"failed to create a resource {res.desc.name}")
        finally:
            self.in_create_resource = False

    # --- arg/call generation ---

    def generate_call(self, s: State, p: Prog) -> List[Call]:
        bias = -1
        if p.calls:
            for _ in range(5):
                c = p.calls[self.intn(len(p.calls))].meta
                bias = c.id
                if c is not self.target.mmap_syscall:
                    break
        if s.ct is None:
            meta = self.target.syscalls[self.intn(len(self.target.syscalls))]
        else:
            meta = self.target.syscalls[s.ct.choose(self.rng, bias)]
        return self.generate_particular_call(s, meta)

    def generate_particular_call(self, s: State, meta: Syscall) -> List[Call]:
        c = Call(meta=meta, ret=ReturnArg(meta.ret))
        c.args, calls = self.generate_args(s, meta.args)
        assign_sizes_call(self.target, c)
        calls = calls + [c]
        for c1 in calls:
            self.target.sanitize_call(c1)
        return calls

    def generate_args(self, s: State, types) -> Tuple[List[Arg], List[Call]]:
        args, calls = [], []
        for t in types:
            arg, calls1 = self.generate_arg(s, t)
            args.append(arg)
            calls.extend(calls1)
        return args, calls

    def generate_arg(self, s: State, typ) -> Tuple[Arg, List[Call]]:
        if typ.dir == Dir.OUT and isinstance(
                typ, (IntType, FlagsType, ConstType, ProcType, VmaType,
                      ResourceType)):
            return default_arg(typ), []

        if typ.optional and self.one_of(5):
            return default_arg(typ), []

        # Bound recursion through optional pointers to structs.
        if isinstance(typ, PtrType) and typ.optional and \
                isinstance(typ.elem, StructType):
            key = typ.elem.name
            if self.rec_depth.get(key, 0) >= 3:
                return PointerArg(typ, 0, 0, 0, None), []
            self.rec_depth[key] = self.rec_depth.get(key, 0) + 1
            try:
                return self._generate_arg_impl(s, typ)
            finally:
                self.rec_depth[key] -= 1
                if not self.rec_depth[key]:
                    del self.rec_depth[key]
        return self._generate_arg_impl(s, typ)

    def _generate_arg_impl(self, s: State, typ) -> Tuple[Arg, List[Call]]:
        if isinstance(typ, ResourceType):
            if self.n_out_of(1000, 1011):
                allres = []
                for name1, res1 in sorted(s.resources.items()):
                    if self.target.is_compatible_resource(typ.desc.name, name1) \
                            or (self.one_of(20) and
                                self.target.is_compatible_resource(
                                    typ.desc.kind[0], name1)):
                        allres.extend(res1)
                if allres:
                    return make_result_arg(
                        typ, allres[self.intn(len(allres))], 0), []
                return self.create_resource(s, typ)
            if self.n_out_of(10, 11):
                return self.create_resource(s, typ)
            special = typ.special_values
            return make_result_arg(
                typ, None, special[self.intn(len(special))]), []

        if isinstance(typ, BufferType):
            if typ.kind in (BufferKind.BLOB_RAND, BufferKind.BLOB_RANGE):
                if typ.kind == BufferKind.BLOB_RANGE:
                    sz = self.rand_range(typ.range_begin, typ.range_end)
                else:
                    sz = self.rand_buf_len()
                if typ.dir == Dir.OUT:
                    return DataArg(typ, b"\x00" * sz), []
                return DataArg(typ, self.rng.randbytes(sz)), []
            if typ.kind == BufferKind.STRING:
                return DataArg(typ, self.rand_string(s, typ.values, typ.dir)), []
            if typ.kind == BufferKind.FILENAME:
                if typ.dir == Dir.OUT:
                    if self.n_out_of(1, 3):
                        n = self.intn(100)
                    elif self.n_out_of(1, 2):
                        n = 108
                    else:
                        n = 4096
                    return DataArg(typ, b"\x00" * n), []
                return DataArg(typ, self.filename(s)), []
            if typ.kind == BufferKind.TEXT:
                return DataArg(typ, self.generate_text(typ.text)), []
            raise TypeError(f"unknown buffer kind {typ.kind}")

        if isinstance(typ, VmaType):
            npages = self.rand_page_count()
            if typ.range_begin or typ.range_end:
                npages = typ.range_begin + self.intn(
                    typ.range_end - typ.range_begin + 1)
            return self.rand_page_addr(s, typ, npages, None, True), []

        if isinstance(typ, FlagsType):
            return ConstArg(typ, self.flags(typ.vals)), []
        if isinstance(typ, ConstType):
            return ConstArg(typ, typ.val), []
        if isinstance(typ, IntType):
            if typ.kind == IntKind.FILEOFF:
                if self.n_out_of(90, 101):
                    v = 0
                elif self.n_out_of(10, 11):
                    v = self.rand(100)
                else:
                    v = self.rand_int()
            elif typ.kind == IntKind.RANGE:
                v = self.rand_range_int(typ.range_begin, typ.range_end)
            else:
                v = self.rand_int()
            return ConstArg(typ, v), []
        if isinstance(typ, ProcType):
            return ConstArg(typ, self.rand(max(1, typ.values_per_proc))), []
        if isinstance(typ, ArrayType):
            if typ.kind == ArrayKind.RAND_LEN:
                count = self.rand_array_len()
            else:
                count = self.rand_range(typ.range_begin, typ.range_end)
            inner, calls = [], []
            for _ in range(count):
                a, cl = self.generate_arg(s, typ.elem)
                inner.append(a)
                calls.extend(cl)
            return GroupArg(typ, inner), calls
        if isinstance(typ, StructType):
            gen = self.target.special_structs.get(typ.name)
            if gen is not None and typ.dir != Dir.OUT:
                return gen(self, s, typ, None)
            args, calls = self.generate_args(s, typ.fields)
            return GroupArg(typ, args), calls
        if isinstance(typ, UnionType):
            opt_t = typ.fields[self.intn(len(typ.fields))]
            opt, calls = self.generate_arg(s, opt_t)
            return UnionArg(typ, opt, opt_t), calls
        if isinstance(typ, PtrType):
            inner, calls = self.generate_arg(s, typ.elem)
            arg, calls1 = self.addr(s, typ, inner.size(), inner)
            return arg, calls + calls1
        if isinstance(typ, LenType):
            return ConstArg(typ, 0), []  # assigned by assign_sizes_call
        if isinstance(typ, CsumType):
            return ConstArg(typ, 0), []  # computed by the executor
        raise TypeError(f"unknown type {typ}")


def generate(target, rng_or_seed, ncalls: int, ct=None) -> Prog:
    """Generate a random program of up to ncalls calls (reference:
    /root/reference/prog/generation.go:12-31)."""
    r = rng_or_seed if isinstance(rng_or_seed, RandGen) \
        else RandGen(target, seed=rng_or_seed)
    p = Prog(target, [])
    s = State(target, ct)
    while len(p.calls) < ncalls:
        calls = r.generate_call(s, p)
        for c in calls:
            s.analyze(c)
            p.calls.append(c)
    if len(p.calls) > ncalls:
        for i in range(len(p.calls) - 1, ncalls - 1, -1):
            p.remove_call(i)
    return p
