"""Extract executed programs from fuzzer console logs.

Capability parity with reference /root/reference/prog/parse.go:22-71
(Target.ParseLog): scan for `executing program N:` markers (optionally
carrying fault-injection parameters), then deserialize the program text
that follows each marker. Used by the repro pipeline to recover the
programs that ran right before a crash.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List

from .encoding import deserialize
from .prog import Prog

_EXECUTING = re.compile(
    r"executing program (\d+)"
    r"(?: \(fault-call:(-?\d+) fault-nth:(\d+)\))?:")


@dataclass
class LogEntry:
    p: Prog
    proc: int = 0
    start: int = 0  # character offset of the entry in the log
    end: int = 0
    fault: bool = False
    fault_call: int = -1
    fault_nth: int = 0


def parse_log(target, data: str) -> List[LogEntry]:
    entries: List[LogEntry] = []
    lines = data.splitlines(keepends=True)
    pos = 0
    i = 0
    while i < len(lines):
        line = lines[i]
        m = _EXECUTING.search(line)
        start = pos
        pos += len(line)
        i += 1
        if not m:
            continue
        # Collect candidate program lines until a blank line or the next
        # marker; tolerate trailing junk by trying progressively shorter
        # prefixes (the reference deserializes the whole chunk and drops
        # unparsable entries; crashes truncate logs mid-line).
        chunk: List[str] = []
        chunk_end = pos
        while i < len(lines):
            nxt = lines[i]
            if not nxt.strip() or _EXECUTING.search(nxt):
                break
            chunk.append(nxt)
            chunk_end += len(nxt)
            pos += len(nxt)
            i += 1
        p = _try_parse(target, chunk)
        if p is None or not p.calls:
            continue
        ent = LogEntry(p=p, proc=int(m.group(1)), start=start, end=chunk_end)
        if m.group(2) is not None:
            ent.fault = True
            ent.fault_call = int(m.group(2))
            ent.fault_nth = int(m.group(3))
        entries.append(ent)
    return entries


def _try_parse(target, chunk: List[str]) -> Prog | None:
    for end in range(len(chunk), 0, -1):
        try:
            return deserialize(target, "".join(chunk[:end]))
        except Exception:
            continue
    return None
