"""Binary serialization of programs for the in-VM executor.

Byte-compatible with the reference's executor wire format (reference:
/root/reference/prog/encodingexec.go:14-288): a flat little-endian u64
instruction stream of copyin/copyout markers, typed arg words, and an EOF
sentinel, with pointers resolved to physical data-arena addresses
(page_index*page_size + data_offset + page_offset). This is also the
program<->tensor boundary format: the device tensor encoding in
prog/tensor.py flattens to the same word stream.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from .prog import (
    Arg,
    Call,
    ConstArg,
    DataArg,
    GroupArg,
    PointerArg,
    Prog,
    ResultArg,
    ReturnArg,
    UnionArg,
    foreach_subarg,
    foreach_subarg_offset,
)
from .checksum import calc_checksums
from .types import CsumType, Dir, PtrType, UINT64_MAX, VmaType, is_pad

# Instruction markers (top of the u64 space, descending).
EXEC_INSTR_EOF = UINT64_MAX
EXEC_INSTR_COPYIN = UINT64_MAX - 1
EXEC_INSTR_COPYOUT = UINT64_MAX - 2

# Arg kinds.
EXEC_ARG_CONST = 0
EXEC_ARG_RESULT = 1
EXEC_ARG_DATA = 2
EXEC_ARG_CSUM = 3

EXEC_ARG_CSUM_INET = 0
EXEC_ARG_CSUM_CHUNK_DATA = 0
EXEC_ARG_CSUM_CHUNK_CONST = 1

EXEC_BUFFER_SIZE = 2 << 20

_U64 = struct.Struct("<Q")


class ExecBufferTooSmall(Exception):
    pass


class _Writer:
    def __init__(self, limit: int):
        self.parts: List[bytes] = []
        self.size = 0
        self.limit = limit

    def word(self, v: int) -> None:
        self.size += 8
        if self.size > self.limit:
            raise ExecBufferTooSmall()
        self.parts.append(_U64.pack(v & UINT64_MAX))

    def data(self, b: bytes) -> None:
        pad = (8 - len(b) % 8) % 8
        self.size += len(b) + pad
        if self.size > self.limit:
            raise ExecBufferTooSmall()
        self.parts.append(b + b"\x00" * pad)

    def bytes(self) -> bytes:
        return b"".join(self.parts)


def physical_addr(target, arg: PointerArg) -> int:
    addr = arg.page_index * target.page_size + target.data_offset
    if arg.page_offset >= 0:
        addr += arg.page_offset
    else:
        addr += target.page_size - (-arg.page_offset)
    return addr


def serialize_for_exec(p: Prog, pid: int = 0,
                       limit: int = EXEC_BUFFER_SIZE,
                       trace=None) -> bytes:
    """Serialize program p for execution by process `pid`.

    `trace(role, arg, word_index)` (optional) is called for every word
    whose value depends on per-program state — the hook prog/execgen.py
    uses to compile static per-syscall exec templates with patch tables.
    Roles: "value" (ConstArg value word), "result" (ResultArg 5-word group
    start), "addr" (any word containing a page-derived physical address),
    "data" (payload word run start), "call" (the call-id word).
    """
    target = p.target
    w = _Writer(limit)
    # arg identity -> (physical addr, instruction index)
    addr_of: Dict[int, int] = {}
    idx_of: Dict[int, int] = {}
    instr_seq = 0

    def pos() -> int:
        return w.size // 8

    def write_arg(arg: Arg) -> None:
        if isinstance(arg, ConstArg):
            w.word(EXEC_ARG_CONST)
            w.word(arg.size())
            if trace is not None:
                trace("value", arg, pos())
            # csum fields must land as zero: the executor's checksum
            # instruction sums the enclosing range with this field included
            # before overwriting it (a stray value would poison the sum).
            w.word(0 if isinstance(arg.typ, CsumType) else arg.value(pid))
            w.word(arg.typ.bitfield_offset)
            w.word(arg.typ.bitfield_length)
        elif isinstance(arg, ResultArg):
            if trace is not None:
                trace("result", arg, pos())
            if arg.res is None:
                w.word(EXEC_ARG_CONST)
                w.word(arg.size())
                w.word(arg.val)
                w.word(0)
                w.word(0)
            else:
                w.word(EXEC_ARG_RESULT)
                w.word(arg.size())
                w.word(idx_of[id(arg.res)])
                w.word(arg.op_div)
                w.word(arg.op_add)
        elif isinstance(arg, PointerArg):
            w.word(EXEC_ARG_CONST)
            w.word(arg.size())
            if trace is not None:
                trace("addr", arg, pos())
            w.word(physical_addr(target, arg))
            w.word(0)
            w.word(0)
        elif isinstance(arg, DataArg):
            w.word(EXEC_ARG_DATA)
            w.word(len(arg.data))
            if trace is not None:
                trace("data", arg, pos())
            w.data(arg.data)
        else:
            raise TypeError(f"cannot exec-serialize arg {arg}")

    for c in p.calls:
        # --- copyins for every pointer pointee ---
        def gen_copyins(arg: Arg, _base):
            nonlocal instr_seq
            if not isinstance(arg, PointerArg) or arg.res is None:
                return
            base_addr = physical_addr(target, arg)

            def per_sub(sub: Arg, offset: int):
                nonlocal instr_seq
                if isinstance(sub, (ResultArg, ReturnArg)) and sub.uses:
                    addr_of[id(sub)] = base_addr + offset
                if isinstance(sub, (GroupArg, UnionArg, ReturnArg)):
                    return
                if isinstance(sub, DataArg) and len(sub.data) == 0:
                    return
                if is_pad(sub.typ) or sub.typ.dir == Dir.OUT:
                    return
                w.word(EXEC_INSTR_COPYIN)
                if trace is not None:
                    trace("addr", arg, pos())
                w.word(base_addr + offset)
                write_arg(sub)
                instr_seq += 1

            foreach_subarg_offset(arg.res, per_sub)

        for a in c.args:
            foreach_subarg(a, gen_copyins)

        # --- checksum instructions (after the data they sum over) ---
        def gen_csums(arg: Arg, _base):
            nonlocal instr_seq
            if not isinstance(arg, PointerArg) or arg.res is None:
                return
            base_addr = physical_addr(target, arg)
            for ci in calc_checksums(arg.res):
                w.word(EXEC_INSTR_COPYIN)
                if trace is not None:
                    trace("addr", arg, pos())
                w.word(base_addr + ci.offset)
                w.word(EXEC_ARG_CSUM)
                w.word(ci.size)
                w.word(EXEC_ARG_CSUM_INET)
                w.word(len(ci.chunks))
                for ch in ci.chunks:
                    w.word(ch.kind)
                    if ch.kind == EXEC_ARG_CSUM_CHUNK_DATA:
                        if trace is not None:
                            trace("addr", arg, pos())
                        w.word(base_addr + ch.value)
                    else:
                        w.word(ch.value)
                    w.word(ch.size)
                instr_seq += 1

        for a in c.args:
            foreach_subarg(a, gen_csums)

        # --- the call itself ---
        if trace is not None:
            trace("call", c, pos())
        w.word(c.meta.id)
        w.word(len(c.args))
        for a in c.args:
            write_arg(a)
        if c.ret is not None and c.ret.uses:
            idx_of[id(c.ret)] = instr_seq
        instr_seq += 1

        # --- copyouts for kernel-written results inside pointees ---
        def gen_copyouts(arg: Arg, _base):
            nonlocal instr_seq
            if isinstance(arg, ResultArg) and arg.uses:
                w.word(EXEC_INSTR_COPYOUT)
                if trace is not None:
                    trace("copyout", arg, pos())
                w.word(addr_of[id(arg)])
                w.word(arg.size())
                idx_of[id(arg)] = instr_seq
                instr_seq += 1

        for a in c.args:
            foreach_subarg(a, gen_copyouts)

    w.word(EXEC_INSTR_EOF)
    return w.bytes()


def decode_exec(data: bytes) -> List[dict]:
    """Decode an exec stream back into a structured instruction list (used by
    tests and the mock executor; the C++ executor implements the same walk)."""
    words = [(_U64.unpack_from(data, i)[0]) for i in range(0, len(data), 8)]
    out: List[dict] = []
    i = 0

    def arg(i: int) -> Tuple[dict, int]:
        kind = words[i]
        if kind == EXEC_ARG_CONST:
            return ({"kind": "const", "size": words[i + 1], "value": words[i + 2],
                     "bf_off": words[i + 3], "bf_len": words[i + 4]}, i + 5)
        if kind == EXEC_ARG_RESULT:
            return ({"kind": "result", "size": words[i + 1], "index": words[i + 2],
                     "div": words[i + 3], "add": words[i + 4]}, i + 5)
        if kind == EXEC_ARG_DATA:
            n = words[i + 1]
            nw = (n + 7) // 8
            raw = data[(i + 2) * 8:(i + 2) * 8 + n]
            return ({"kind": "data", "size": n, "data": raw}, i + 2 + nw)
        if kind == EXEC_ARG_CSUM:
            size = words[i + 1]
            ckind = words[i + 2]
            nchunks = words[i + 3]
            j = i + 4
            chunks = []
            for _ in range(nchunks):
                chunks.append({"kind": words[j], "value": words[j + 1],
                               "size": words[j + 2]})
                j += 3
            return ({"kind": "csum", "size": size, "csum_kind": ckind,
                     "chunks": chunks}, j)
        raise ValueError(f"bad exec arg kind {kind}")

    while i < len(words):
        wv = words[i]
        if wv == EXEC_INSTR_EOF:
            break
        if wv == EXEC_INSTR_COPYIN:
            a, j = arg(i + 2)
            out.append({"op": "copyin", "addr": words[i + 1], "arg": a})
            i = j
        elif wv == EXEC_INSTR_COPYOUT:
            out.append({"op": "copyout", "addr": words[i + 1],
                        "size": words[i + 2]})
            i += 3
        else:
            call_id = wv
            nargs = words[i + 1]
            i += 2
            args = []
            for _ in range(nargs):
                a, i = arg(i)
                args.append(a)
            out.append({"op": "call", "id": call_id, "args": args})
    return out
