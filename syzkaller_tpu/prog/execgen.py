"""Direct tensor-batch -> exec-stream emission (the fast host boundary).

The device mutates candidates at ~10^5 progs/s but the per-program
decode_prog -> Prog -> serialize_for_exec round-trip walks Python trees at
~10^3 progs/s, capping the end-to-end loop (SURVEY §7 hard part #3).  This
module removes the round-trip: because the tensor encoding is built on
*static per-syscall templates* (descriptions/tables.py), the exec-format
word stream of a call (reference prog/encodingexec.go:14-288) is itself
static per syscall id up to a small set of patchable words — argument
values, resource-result indices, page-derived addresses, payload bytes.

Template build: serialize_for_exec runs once per syscall id over the
cap-filled template tree with a trace hook recording which word positions
hold patchable quantities.  Batch emission then copies the template words
and patches them with numpy ops per (row, call) — no tree construction,
no per-word Python.

Fidelity contract vs the decode path (pinned by tests/test_execgen.py):
  - byte-identical to serialize_for_exec(decode_prog(row)) whenever every
    DATA slot's length value >= its cap (the template instantiation);
  - for shorter dynamic lengths the fast path pins payloads at cap (the
    kernel sees a legal full-cap buffer) — the device alley trades length
    exploration for throughput; generate/mutate/smash keep full dynamism;
  - rows containing sanitize-special calls (mmap/mremap/exit/exit_group,
    whose decode applies target.sanitize_call rewrites) return None and
    the caller falls back to decode_prog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..descriptions.tables import (
    SK_DATA,
    SK_LEN,
    SK_PTR,
    SK_REF,
    SK_VALUE,
    SK_VMA,
    CompiledTables,
)
from .analysis import assign_sizes_call
from .encodingexec import (
    EXEC_INSTR_COPYOUT,
    EXEC_INSTR_EOF,
    decode_exec,
    serialize_for_exec,
)
from .prog import (
    Call,
    ConstArg,
    DataArg,
    PointerArg,
    Prog,
    ResultArg,
    ReturnArg,
    foreach_subarg,
    foreach_subarg_offset,
)
from .tensor import (
    REF_NONE,
    VMA_MAX_PAGES,
    ProgBatch,
    TensorFormat,
    _find_source,
    template_arg,
    walk_slots,
)
from .types import Dir, ProcType, ResourceType, UINT64_MAX, VmaType

U64 = np.uint64

# Calls whose decode applies target.sanitize_call rewrites.  For linux the
# three rewrites are pure per-slot value transforms the emitter vectorizes
# (see _SANITIZE_OPS); other targets fall back to the decode path for them.
SANITIZE_CALLS = {"mmap", "mremap", "exit", "exit_group"}


@dataclass
class _CallTemplate:
    words: np.ndarray                    # u64 [L] static skeleton
    n_instr: int                         # copyins + csums + the call itself
    addr_pos: np.ndarray                 # word positions holding page-derived addrs
    # SK_VALUE patches (vectorized)
    val_pos: np.ndarray
    val_slot: np.ndarray
    val_proc_start: np.ndarray           # u64; 0 for non-proc
    val_proc_per: np.ndarray             # u64; 0 for non-proc
    val_be: List[Tuple[int, int]]        # (patch idx, byte size) big-endian swaps
    # sanitize transforms: (patch idx, op, a, b); op in
    # "exit" (v%128 in {67,68} -> 1), "or" (v|a), "ifand_or" (v|b if v&a)
    val_san: List[Tuple[int, str, int, int]]
    # SK_REF patches: (group word pos, slot, size)
    refs: List[Tuple[int, int, int]]
    ref_res_name: List[str]              # resource name per ref entry
    # SK_VMA patches: (addr word pos, slot)
    vmas: List[Tuple[int, int]]
    # LEN-of-vma patches: (value word pos, target slot)
    vma_lens: List[Tuple[int, int]]
    # payload byte runs: (byte offset in stream, arena offset, cap)
    datas: List[Tuple[int, int, int]]
    # copyout candidates: DFS rank -> (rank, addr const, size, paged)
    copyout: Dict[int, Tuple[int, int, int, bool]]
    copyout_rank_of: Dict[int, int]      # id(template node) -> rank
    resolve: Dict[str, object] = field(default_factory=dict)  # res -> "ret"|si|None
    tree_call: object = None             # template Call (for _find_source)


def _swap(v: int, size: int) -> int:
    return int.from_bytes(int(v).to_bytes(size, "little"), "big")


class ExecGen:
    def __init__(self, tables: CompiledTables, fmt: TensorFormat):
        self.tables = tables
        self.fmt = fmt
        self.target = tables.target
        self.psize = self.target.page_size
        self._tmpl: Dict[int, Optional[_CallTemplate]] = {}
        self._prelude: Optional[Tuple[np.ndarray, int]] = None  # words, len pos

    # ---- template build -------------------------------------------------

    def _build_prelude(self) -> Tuple[np.ndarray, int]:
        if self._prelude is None:
            c = self.target.make_mmap(0, 1)
            length_arg = c.args[1]
            traces: List[Tuple[str, object, int]] = []
            data = serialize_for_exec(
                Prog(self.target, [c]), 0,
                trace=lambda r, a, i: traces.append((r, a, i)))
            words = np.frombuffer(data, dtype=np.uint64)[:-1].copy()  # drop EOF
            pos = next(i for r, a, i in traces
                       if r == "value" and a is length_arg)
            self._prelude = (words, pos)
        return self._prelude

    def _template(self, cid: int) -> Optional[_CallTemplate]:
        if cid in self._tmpl:
            return self._tmpl[cid]
        t = None
        try:
            t = self._build_template(cid)
        except Exception:
            t = None
        self._tmpl[cid] = t
        return t

    def _build_template(self, cid: int) -> Optional[_CallTemplate]:
        tables, fmt = self.tables, self.fmt
        meta = self.target.syscalls[cid]
        if meta.call_name in SANITIZE_CALLS and (
                self.target.os != "linux"
                or "MAP_FIXED" not in self.target.consts):
            return None
        args = [template_arg(tt) for tt in meta.args]
        call = Call(meta=meta, args=args,
                    ret=ReturnArg(meta.ret) if meta.ret is not None
                    else ReturnArg(None))

        off = int(tables.call_slot_off[cid])
        cnt = int(tables.call_slot_cnt[cid])
        bo = int(tables.call_block_off[cid])
        limit = min(cnt, fmt.max_slots)

        # slot map over the template tree (the exact decode_prog walk)
        slot_of: Dict[int, Tuple[int, int]] = {}
        slots: List[Tuple[object, int]] = []
        for si, (arg, kind) in enumerate(walk_slots(args)):
            slots.append((arg, kind))
            if si < limit:
                slot_of[id(arg)] = (si, kind)

        # instantiate at template shape: caps for payloads, table layout
        # for pointers/vmas (mirrors decode_prog with call_page=1)
        datas_arena: Dict[int, int] = {}
        patched_ptrs: set = set()
        for si, (arg, kind) in enumerate(slots):
            if si >= limit:
                break
            if kind == SK_DATA:
                cap = int(tables.slot_size[off + si])
                blk = int(tables.slot_block[off + si])
                if blk >= 0:
                    base = int(tables.block_addr[bo + blk]) + \
                        int(tables.slot_offset[off + si])
                    datas_arena[si] = base
                arg.data = b"\x00" * cap
            elif kind == SK_PTR:
                blk = int(tables.slot_target_block[off + si])
                if isinstance(arg, PointerArg) and blk >= 0:
                    arg.page_index = 1
                    arg.page_offset = int(tables.block_addr[bo + blk])
                    patched_ptrs.add(id(arg))
            elif kind == SK_VMA:
                arg.page_index = 0
                arg.page_offset = 0
                arg.pages_num = 1
        assign_sizes_call(self.target, call)

        traces: List[Tuple[str, object, int]] = []
        data = serialize_for_exec(
            Prog(self.target, [call]), 0,
            trace=lambda r, a, i: traces.append((r, a, i)))
        words = np.frombuffer(data, dtype=np.uint64)[:-1].copy()  # drop EOF
        n_instr = sum(1 for ins in decode_exec(data)
                      if ins["op"] in ("copyin", "call"))

        addr_pos: List[int] = []
        val_pos: List[int] = []
        val_slot: List[int] = []
        val_ps: List[int] = []
        val_pp: List[int] = []
        val_be: List[Tuple[int, int]] = []
        refs: List[Tuple[int, int, int]] = []
        ref_res: List[str] = []
        vmas: List[Tuple[int, int]] = []
        vma_lens: List[Tuple[int, int]] = []
        datas: List[Tuple[int, int, int]] = []

        vma_slots = {si for si, (a, k) in enumerate(slots)
                     if k == SK_VMA and si < limit}
        vma_args = {id(a): si for si, (a, k) in enumerate(slots)
                    if k == SK_VMA and si < limit}

        for role, arg, pos in traces:
            if role == "addr":
                if id(arg) in vma_args:
                    vmas.append((pos, vma_args[id(arg)]))
                elif id(arg) in patched_ptrs:
                    # only pointers decode rebased onto the call page get
                    # the per-row page term; slotless / blockless pointers
                    # stay at page 0 in both paths
                    addr_pos.append(pos)
            elif role == "value":
                ent = slot_of.get(id(arg))
                if ent is None:
                    continue
                si, kind = ent
                if kind == SK_VALUE:
                    tt = arg.typ
                    val_pos.append(pos)
                    val_slot.append(si)
                    if isinstance(tt, ProcType):
                        val_ps.append(tt.values_start)
                        val_pp.append(tt.values_per_proc)
                    else:
                        val_ps.append(0)
                        val_pp.append(0)
                    if getattr(tt, "big_endian", False):
                        val_be.append((len(val_pos) - 1, tt.size))
                elif kind == SK_LEN:
                    # only vma-targeting lens are dynamic in the fast path
                    lt = int(tables.slot_len_target[off + si]) \
                        if si < cnt else -1
                    if lt in vma_slots:
                        vma_lens.append((pos, lt))
            elif role == "result":
                ent = slot_of.get(id(arg))
                if ent is None:
                    continue
                si, kind = ent
                if kind == SK_REF:
                    refs.append((pos, si, arg.size()))
                    ref_res.append(arg.typ.desc.name)
                elif kind == SK_VALUE:
                    # out-dir resource slot: raw val patch (ResultArg path
                    # writes arg.val with no endian/proc transform) — the
                    # value word is group word 2
                    val_pos.append(pos + 2)
                    val_slot.append(si)
                    val_ps.append(0)
                    val_pp.append(0)
            elif role == "data":
                ent = slot_of.get(id(arg))
                if ent is None or ent[1] != SK_DATA:
                    continue
                si = ent[0]
                if si in datas_arena:
                    datas.append((pos * 8, datas_arena[si],
                                  int(tables.slot_size[off + si])))

        # copyout candidates: out-dir resource nodes inside pointees, with
        # addresses from the copyin layout and ranks in gen_copyouts' DFS
        # order (encodingexec.py:gen_copyouts — full foreach_subarg walk,
        # which interleaves nested pointees at their pointer's position)
        from .encodingexec import physical_addr

        addr_map: Dict[int, Tuple[int, bool]] = {}

        def per_ptr(parg, _b):
            if not isinstance(parg, PointerArg) or parg.res is None:
                return
            base = physical_addr(self.target, parg)
            paged = id(parg) in patched_ptrs
            foreach_subarg_offset(
                parg.res,
                lambda sub, offset: addr_map.__setitem__(
                    id(sub), (base + offset, paged)))

        for a in call.args:
            foreach_subarg(a, per_ptr)

        # keyed by DFS rank, not slot index: decode's _find_source can bind
        # to out-dir nodes beyond the slot budget (large structs), and the
        # copyout must still be emitted for them
        copyout: Dict[int, Tuple[int, int, int]] = {}
        copyout_rank_of: Dict[int, int] = {}
        rank = [0]

        def per_node(sub, _b):
            if isinstance(sub, ResultArg) and \
                    isinstance(sub.typ, ResourceType) and \
                    sub.typ.dir != Dir.IN and id(sub) in addr_map:
                addr, paged = addr_map[id(sub)]
                copyout[rank[0]] = (rank[0], addr, sub.size(), paged)
                copyout_rank_of[id(sub)] = rank[0]
                rank[0] += 1

        for a in call.args:
            foreach_subarg(a, per_node)

        # vectorized sanitize_call equivalents (descriptions/linux/__init__
        # sanitize_call): pure value transforms on one top-level arg slot
        val_san: List[Tuple[int, str, int, int]] = []
        cn = meta.call_name
        if cn in SANITIZE_CALLS:
            san_arg = {"mmap": 3, "mremap": 3, "exit": 0, "exit_group": 0}[cn]
            cm = self.target.consts
            for pi, si in enumerate(val_slot):
                if si < cnt and tables.slot_is_arg[off + si] and \
                        int(tables.slot_arg_idx[off + si]) == san_arg:
                    if cn == "mmap":
                        val_san.append((pi, "or", cm["MAP_FIXED"], 0))
                    elif cn == "mremap":
                        val_san.append((pi, "ifand_or",
                                        cm["MREMAP_MAYMOVE"],
                                        cm["MREMAP_FIXED"]))
                    else:
                        val_san.append((pi, "exit", 0, 0))
                    break

        return _CallTemplate(
            words=words, n_instr=n_instr,
            addr_pos=np.asarray(addr_pos, dtype=np.int64),
            val_pos=np.asarray(val_pos, dtype=np.int64),
            val_slot=np.asarray(val_slot, dtype=np.int64),
            val_proc_start=np.asarray(val_ps, dtype=np.uint64),
            val_proc_per=np.asarray(val_pp, dtype=np.uint64),
            val_be=val_be, val_san=val_san, refs=refs,
            ref_res_name=ref_res,
            vmas=vmas, vma_lens=vma_lens, datas=datas, copyout=copyout,
            copyout_rank_of=copyout_rank_of, tree_call=call,
        )

    def _resolve(self, tmpl: _CallTemplate, res_name: str):
        """How a consumer wanting `res_name` binds to this producer call:
        "ret", an inner copyout slot index, or None — memoized; mirrors
        decode_prog's _find_source over the template tree exactly."""
        if res_name in tmpl.resolve:
            return tmpl.resolve[res_name]
        out = None
        desc = self.target.resource_map.get(res_name)
        if desc is not None:
            # any ResourceType of that desc will do for _find_source
            res_type = ResourceType(name=res_name, desc=desc)
            src = _find_source(tmpl.tree_call, res_type, self.target)
            if src is not None and src is tmpl.tree_call.ret:
                out = "ret"
            elif src is not None:
                # only copyout candidates (out-dir resources inside
                # pointees) are addressable
                out = tmpl.copyout_rank_of.get(id(src))
        tmpl.resolve[res_name] = out
        return out

    # ---- emission -------------------------------------------------------

    def emit_row(self, batch: ProgBatch, row: int, pid: int = 0
                 ) -> Optional[bytes]:
        tables, fmt, psize = self.tables, self.fmt, self.psize
        call_id = batch.call_id[row]
        slot_val = batch.slot_val[row]
        data = batch.data[row]

        active: List[Tuple[int, _CallTemplate]] = []
        for ci in range(fmt.max_calls):
            cid = int(call_id[ci])
            if cid < 0:
                continue
            tmpl = self._template(cid)
            if tmpl is None:
                return None  # fallback row
            active.append((ci, tmpl))

        if not active:
            # decode_prog of an empty row yields a call-less prog: no
            # mmap prelude, just EOF
            return np.asarray([EXEC_INSTR_EOF], dtype=np.uint64).tobytes()

        # pass 1: resolve refs -> per-call used copyout slots
        used: List[set] = [set() for _ in active]
        resolved: List[List[Optional[Tuple[int, object]]]] = []
        for k, (ci, tmpl) in enumerate(active):
            res_k: List[Optional[Tuple[int, object]]] = []
            for (pos, si, size), rname in zip(tmpl.refs, tmpl.ref_res_name):
                v = int(slot_val[ci, si])
                if v == REF_NONE or v >= k:
                    res_k.append(None)
                    continue
                how = self._resolve(active[v][1], rname)
                if how is None:
                    res_k.append(None)
                elif how == "ret":
                    res_k.append((v, "ret"))
                else:
                    used[v].add(how)
                    res_k.append((v, how))
            resolved.append(res_k)

        # pass 2: instruction numbering (prelude mmap is instr 0)
        cursor = 1
        call_instr: List[int] = []
        copyout_idx: List[Dict[int, int]] = []
        for k, (ci, tmpl) in enumerate(active):
            call_instr.append(cursor + tmpl.n_instr - 1)
            cursor += tmpl.n_instr
            cmap: Dict[int, int] = {}
            for si in sorted(used[k], key=lambda s: tmpl.copyout[s][0]):
                cmap[si] = cursor
                cursor += 1
            copyout_idx.append(cmap)

        # pass 3: emit
        vma_cursor = fmt.max_calls + 1
        pieces: List[np.ndarray] = []
        for k, (ci, tmpl) in enumerate(active):
            page = 1 + k
            w = tmpl.words.copy()
            if tmpl.addr_pos.size:
                w[tmpl.addr_pos] += U64((page - 1) * psize)
            if tmpl.val_pos.size:
                # proc values: serialize adds start + per*pid to the raw val
                vals = slot_val[ci][tmpl.val_slot] + tmpl.val_proc_start + \
                    tmpl.val_proc_per * U64(pid)
                for pi, op, a, b in tmpl.val_san:
                    v = int(vals[pi])
                    if op == "or":
                        v |= a
                    elif op == "ifand_or" and v & a:
                        v |= b
                    elif op == "exit" and v % 128 in (67, 68):
                        v = 1
                    vals[pi] = U64(v)
                w[tmpl.val_pos] = vals
                for pi, sz in tmpl.val_be:
                    w[tmpl.val_pos[pi]] = U64(_swap(
                        int(vals[pi]) & ((1 << (8 * sz)) - 1), sz))
            for ri, ent in enumerate(resolved[k]):
                if ent is None:
                    continue
                pos, _si, _size = tmpl.refs[ri]
                v, how = ent
                w[pos] = U64(1)  # EXEC_ARG_RESULT
                if how == "ret":
                    w[pos + 2] = U64(call_instr[v])
                else:
                    w[pos + 2] = U64(copyout_idx[v][how])
                w[pos + 3] = U64(0)
                w[pos + 4] = U64(0)
            for pos, si in tmpl.vmas:
                pages = max(1, min(int(slot_val[ci, si]), VMA_MAX_PAGES))
                w[pos] = U64(self.target.data_offset + vma_cursor * psize)
                vma_cursor += pages
                # remember per-slot page count for the len patch below
            for pos, si in tmpl.vma_lens:
                pages = max(1, min(int(slot_val[ci, si]), VMA_MAX_PAGES))
                w[pos] = U64(pages * psize)
            if tmpl.datas:
                bv = w.view(np.uint8)
                for bpos, abase, cap in tmpl.datas:
                    bv[bpos:bpos + cap] = data[ci, abase:abase + cap]
            pieces.append(w)
            if copyout_idx[k]:
                co = np.empty(3 * len(copyout_idx[k]), dtype=np.uint64)
                j = 0
                for si in sorted(copyout_idx[k],
                                 key=lambda s: tmpl.copyout[s][0]):
                    _rank, addr, size, paged = tmpl.copyout[si]
                    co[j] = U64(EXEC_INSTR_COPYOUT)
                    co[j + 1] = U64(addr + (page - 1) * psize if paged
                                    else addr)
                    co[j + 2] = U64(size)
                    j += 3
                pieces.append(co)

        prelude, len_pos = self._build_prelude()
        pre = prelude.copy()
        npages = max(vma_cursor, 1 + len(active))
        pre[len_pos] = U64(npages * psize)
        eof = np.asarray([EXEC_INSTR_EOF], dtype=np.uint64)
        return np.concatenate([pre, *pieces, eof]).tobytes()

    def emit_batch(self, batch: ProgBatch, pid: int = 0
                   ) -> List[Optional[bytes]]:
        return [self.emit_row(batch, r, pid) for r in range(batch.batch)]
