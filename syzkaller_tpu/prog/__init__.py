from .types import (  # noqa: F401
    ArrayKind, ArrayType, BufferKind, BufferType, ConstType, CsumKind,
    CsumType, Dir, FlagsType, IntKind, IntType, LenType, ProcType, PtrType,
    ResourceDesc, ResourceType, StructType, Syscall, TextKind, Type,
    UnionType, VmaType, foreach_type, is_pad,
)
from .prog import (  # noqa: F401
    Arg, Call, ConstArg, DataArg, GroupArg, PointerArg, Prog, ResultArg,
    ReturnArg, UnionArg, default_arg, foreach_arg, foreach_subarg,
    foreach_subarg_offset, inner_arg, make_result_arg,
)
from .target import Target, all_targets, get_target, register_target  # noqa: F401
