"""Target: one OS/arch pair — its syscall surface, resources, and arch hooks.

Capability parity with reference /root/reference/prog/target.go:12-148 and
/root/reference/prog/resources.go (ctor discovery, resource compatibility
lattice, transitively-enabled-call fixpoint). The compiled numpy tables the
TPU kernels consume are derived from this object by
`syzkaller_tpu.descriptions.tables.compile_tables`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .types import (
    Dir,
    ResourceDesc,
    ResourceType,
    StructType,
    Syscall,
    Type,
    foreach_type,
)


def is_compatible_resource_kinds(dst: Sequence[str], src: Sequence[str],
                                 precise: bool = False) -> bool:
    """True if a resource of kind chain `src` can be passed where `dst` is
    expected. Kind chains are most-general-first (e.g. ("fd", "sock")).
    Imprecise mode allows passing a less specialized resource (fd as sock)."""
    if len(dst) > len(src):
        if precise:
            return False
        dst = dst[: len(src)]
    if len(src) > len(dst):
        src = src[: len(dst)]
    return all(d == s for d, s in zip(dst, src))


class Target:
    def __init__(self, os: str, arch: str, *, ptr_size: int = 8,
                 page_size: int = 4096, data_offset: int = 0x10000000,
                 num_pages: int = 4096, revision: str = "",
                 syscalls: Optional[List[Syscall]] = None,
                 resources: Optional[List[ResourceDesc]] = None,
                 consts: Optional[Dict[str, int]] = None):
        self.os = os
        self.arch = arch
        self.revision = revision
        self.ptr_size = ptr_size
        self.page_size = page_size
        self.data_offset = data_offset
        self.num_pages = num_pages  # size of the data arena in pages

        self.syscalls: List[Syscall] = syscalls or []
        self.resources: List[ResourceDesc] = resources or []
        self.consts: Dict[str, int] = dict(consts or {})

        self.syscall_map: Dict[str, Syscall] = {c.name: c for c in self.syscalls}
        self.resource_map: Dict[str, ResourceDesc] = {r.name: r for r in self.resources}
        # resource name -> calls that can create it (imprecise match)
        self.resource_ctors: Dict[str, List[Syscall]] = {
            r.name: self.calc_resource_ctors(r.kind, precise=False)
            for r in self.resources
        }

        # --- arch hooks, overridable by OS modules ---
        self.mmap_syscall: Optional[Syscall] = None
        self.make_mmap: Callable[[int, int], object] = self._no_mmap
        self.analyze_mmap: Callable[[object], Tuple[int, int, bool]] = (
            lambda c: (0, 0, False))
        self.sanitize_call: Callable[[object], None] = lambda c: None
        self.special_structs: Dict[str, Callable] = {}
        self.string_dictionary: List[str] = []

    def _no_mmap(self, start: int, npages: int):
        raise RuntimeError(f"target {self.os}/{self.arch} has no mmap hook")

    # ---- resources ----

    def calc_resource_ctors(self, kind: Sequence[str],
                            precise: bool) -> List[Syscall]:
        """Calls with an out/inout resource arg compatible with `kind`."""
        metas = []
        for meta in self.syscalls:
            found = [False]

            def visit(t: Type):
                if found[0]:
                    return
                if isinstance(t, ResourceType) and t.dir != Dir.IN:
                    if is_compatible_resource_kinds(tuple(kind), t.desc.kind,
                                                   precise):
                        found[0] = True

            foreach_type(meta, visit)
            if found[0]:
                metas.append(meta)
        return metas

    def is_compatible_resource(self, dst: str, src: str) -> bool:
        return is_compatible_resource_kinds(
            self.resource_map[dst].kind, self.resource_map[src].kind)

    @staticmethod
    def input_resources(meta: Syscall) -> List[ResourceType]:
        res: List[ResourceType] = []

        def visit(t: Type):
            if isinstance(t, ResourceType) and t.dir != Dir.OUT and not t.optional:
                res.append(t)

        foreach_type(meta, visit)
        return res

    def transitively_enabled_calls(
            self, enabled: Sequence[Syscall]) -> List[Syscall]:
        """Fixpoint-prune calls whose required input resources cannot be
        constructed by any other enabled call (precise ctor match)."""
        supported = {c.name: c for c in enabled}
        inputs = {c.name: self.input_resources(c) for c in enabled}
        ctors = {}
        for c in enabled:
            for r in inputs[c.name]:
                if r.desc.name not in ctors:
                    ctors[r.desc.name] = self.calc_resource_ctors(
                        r.desc.kind, precise=True)
        while True:
            n = len(supported)
            for name in list(supported):
                ok = True
                for r in inputs[name]:
                    if not any(ct.name in supported for ct in ctors[r.desc.name]):
                        ok = False
                        break
                if not ok:
                    del supported[name]
            if n == len(supported):
                break
        return [c for c in self.syscalls if c.name in supported]


_targets: Dict[str, Target] = {}


def register_target(target: Target,
                    init_arch: Optional[Callable[[Target], None]] = None) -> None:
    key = f"{target.os}/{target.arch}"
    if key in _targets:
        raise ValueError(f"duplicate target {key}")
    if init_arch is not None:
        init_arch(target)
    _targets[key] = target


def get_target(os: str, arch: str) -> Target:
    key = f"{os}/{arch}"
    if key not in _targets:
        # Lazily build a bundled target from its descriptions package
        # (descriptions/<os>/ — linux, freebsd, fuchsia, windows), the
        # role of the reference's sys/<os>/<arch>.go init() registration
        # (reference: /root/reference/sys/linux/amd64.go:6-8).
        import importlib

        mod_name = f"{__package__.rsplit('.', 1)[0]}.descriptions.{os}"
        try:
            mod = importlib.import_module(mod_name)
        except ModuleNotFoundError as e:
            # Only an unknown OS is a lookup miss; a broken transitive
            # import inside a descriptions package must propagate.
            if e.name != mod_name:
                raise
            mod = None
        if mod is not None:
            from ..descriptions.bundle import UnsupportedArchError

            try:
                mod.ensure_registered(arch)
            except UnsupportedArchError:
                # No bundled consts for this arch: fall through to the
                # uniform unknown-target report below.
                pass
        if key not in _targets:
            raise KeyError(
                f"unknown target {key} (known: {sorted(_targets)})")
    return _targets[key]


def all_targets() -> List[Target]:
    return [_targets[k] for k in sorted(_targets)]
