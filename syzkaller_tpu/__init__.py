"""syzkaller_tpu: a TPU-native coverage-guided kernel-fuzzing framework.

Syzkaller-class capabilities (typed syscall-program generation from
declarative descriptions, coverage-guided mutation/triage, in-VM executor,
crash detection/repro, VM-fleet manager, multi-manager corpus exchange) with
the fuzzing brain implemented as batched JAX/XLA kernels over fixed-width
program tensors. See SURVEY.md at the repo root for the structural map.

Layout:
  descriptions/  syscall description language -> Target -> numpy tables
  prog/          host-side program IR, text + exec serialization, tensors
  ops/           JAX kernels: rng, mutation, generation, prio, cover, hints
  parallel/      device mesh, sharded coverage collectives
  engine/        the fuzzing loop (corpus-as-tensors, triage)
  ipc/ executor/ shared-memory protocol + C++ in-VM executor
  manager/ vm/   host orchestrator, VM-fleet backends
  report/ repro/ crash parsing and automated reproduction
"""

# NOTE: importing the top-level package stays jax-free so the description
# pipeline and program IR work standalone; the device modules
# (ops/, parallel/, engine/) call utils.jaxcfg.ensure_x64() which
# enables 64-bit lanes (program words and signal hashes are u64/u32).

__version__ = "0.1.0"
