"""Seeded fault-injection harness: deterministic chaos for the engine.

Reference syzkaller earns its robustness claims by construction (VMs are
disposable, the corpus persists, vmLoop reschedules) but has no way to
*prove* them hermetically.  This module closes that gap: a ``FaultPlan``
schedules faults at exact occurrence counts per *site* (or draws them at
a seeded rate), tests ``install()`` it, and the production paths consult
the plan through two hooks:

    should_fire(site) -> bool   # caller implements the failure itself
    fire(site)                  # raises InjectedFault when scheduled

Sites in use (grep for the literals):

    ``env.exec:<pid>``  — ipc Env/MockEnv exec_raw: the executor "dies"
                          (real proc killed / mock reports failed), which
                          the drain supervisor must survive by re-sharding
                          the row across surviving envs;
    ``rpc.poll``        — engine poll_manager (fired once per sync,
                          whatever the manager type): one sync fails,
                          the campaign must not;
    ``rpc.transport.<method>`` — RemoteManager transport attempts (fired
                          once per attempt): exercises the retry /
                          reconnect loop specifically;
    ``device.step``     — _DevicePipeline launch: the XLA step raises and
                          the degradation ladder (retry -> recompile ->
                          host fallback) must catch it.

Hooks are NO-OPS when no plan is installed (one module-global read), so
production binaries pay nothing.  Occurrence counting is per-site and
1-based: ``fail_at("rpc.poll", 1)`` fails the first poll only.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Tuple


class InjectedFault(RuntimeError):
    """An error raised on purpose by an installed FaultPlan."""


class FaultPlan:
    """Deterministic fault schedule: explicit per-site occurrence indices
    plus optional seeded random rates.  Thread-safe — the drain workers
    hit ``env.exec:*`` sites concurrently."""

    def __init__(self, seed: int = 0,
                 rates: Optional[Dict[str, float]] = None):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sched: Dict[str, set] = {}
        self._rates: Dict[str, float] = dict(rates or {})
        self._counts: Dict[str, int] = {}
        self._fired: List[Tuple[str, int]] = []

    def fail_at(self, site: str, *occurrences: int) -> "FaultPlan":
        """Schedule failures at the given 1-based occurrence indices of
        ``site``; returns self so plans read as one chained literal."""
        self._sched.setdefault(site, set()).update(occurrences)
        return self

    def rate(self, site: str, p: float) -> "FaultPlan":
        """Additionally fail ``site`` with probability ``p`` per
        occurrence (seeded — the same plan replays identically)."""
        self._rates[site] = p
        return self

    def should_fire(self, site: str) -> bool:
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            hit = n in self._sched.get(site, ())
            p = self._rates.get(site, 0.0)
            if not hit and p > 0.0 and self._rng.random() < p:
                hit = True
            if hit:
                self._fired.append((site, n))
            return hit

    def fired(self) -> List[Tuple[str, int]]:
        """(site, occurrence) log of every fault this plan delivered."""
        with self._lock:
            return list(self._fired)

    def count(self, site: str) -> int:
        """How many times ``site`` has been consulted."""
        with self._lock:
            return self._counts.get(site, 0)


_active: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> None:
    """Make ``plan`` the process-wide active plan (None to disarm)."""
    global _active
    _active = plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    return _active


def should_fire(site: str) -> bool:
    """Hook for call sites that implement the failure themselves (the
    ipc env-death simulation).  No plan installed -> always False."""
    p = _active
    return p is not None and p.should_fire(site)


def fire(site: str) -> None:
    """Hook for call sites where a raised exception IS the failure mode
    (RPC calls, device steps).  No plan installed -> no-op."""
    p = _active
    if p is not None and p.should_fire(site):
        raise InjectedFault(site)
