"""Deterministic chaos tooling for the campaign-supervision layer.

``faults`` holds the seeded fault-injection harness (FaultPlan): tests
install a plan that kills executor envs, raises on manager RPC, and
poisons device steps at chosen occurrences, and the production paths
consult it through near-zero-cost module hooks.  Nothing here imports
jax/numpy — installing no plan must cost one global read per hook site.
"""

from . import faults  # noqa: F401
