"""adb VM backend: physical Android devices over USB.

Role parity with reference /root/reference/vm/adb/adb.go:27-...: each
pool index is a device serial; copy = `adb push`, run = `adb shell`,
manager reachability via `adb reverse`; close kills the shell and
best-effort reboots on request.  Console output is the device's dmesg
stream merged with the command output (the reference reads a USB-serial
console; dmesg -w is the toolless equivalent).
"""

from __future__ import annotations

import os
import signal
import subprocess
from typing import List, Tuple

from . import Instance, OutputMerger, Pool, VMConfig, register_backend


@register_backend("adb")
class AdbPool(Pool):
    @property
    def count(self) -> int:
        return len(self.cfg.targets)

    def create(self, index: int) -> "AdbInstance":
        return AdbInstance(self.cfg, index)


class AdbInstance(Instance):
    def __init__(self, cfg: VMConfig, index: int):
        if not cfg.targets:
            raise ValueError("adb backend needs device serials in targets")
        self.cfg = cfg
        self.index = index
        self.serial = cfg.targets[index % len(cfg.targets)]
        self._procs: List[subprocess.Popen] = []
        self._dmesg = None
        self._reversed: List[int] = []
        self._adb(["wait-for-device"], timeout=120)
        self._adb(["shell", f"mkdir -p {cfg.target_dir}"])

    def _adb(self, args: List[str], timeout: float = 60.0,
             check: bool = True):
        return subprocess.run(["adb", "-s", self.serial, *args],
                              capture_output=True, timeout=timeout,
                              check=check)

    def copy(self, host_src: str) -> str:
        dst = f"{self.cfg.target_dir}/{os.path.basename(host_src)}"
        self._adb(["push", host_src, dst], timeout=300)
        self._adb(["shell", f"chmod 755 {dst}"])
        return dst

    def forward(self, port: int) -> str:
        # reverse: device connections to localhost:port reach the host
        self._adb(["reverse", f"tcp:{port}", f"tcp:{port}"])
        self._reversed.append(port)
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        merger = OutputMerger()
        # console: kernel log stream alongside the command's own output;
        # one watcher per instance — kill the previous run's stream
        if self._dmesg is not None and self._dmesg.poll() is None:
            try:
                os.killpg(os.getpgid(self._dmesg.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        self._dmesg = subprocess.Popen(
            ["adb", "-s", self.serial, "shell", "dmesg -w"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            start_new_session=True)
        self._procs.append(self._dmesg)
        merger.attach(self._dmesg.stdout, finish=False)
        proc = subprocess.Popen(
            ["adb", "-s", self.serial, "shell", command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        merger.attach(proc.stdout, finish=False)
        return merger, proc

    def close(self) -> None:
        try:
            self._adb(["shell", "pkill -f syzkaller_tpu; "
                       "pkill -f syz-executor; true"], check=False)
        except Exception:
            pass
        for port in self._reversed:
            try:
                self._adb(["reverse", "--remove", f"tcp:{port}"],
                          check=False)
            except Exception:
                pass
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        if self.cfg.target_reboot:
            try:
                self._adb(["reboot"], check=False)
            except Exception:
                pass
