"""GCE VM backend, via the gcloud CLI.

Role parity with reference /root/reference/vm/gce/gce.go:36-... (+
pkg/gce API wrapper): boot instances from an image, ssh in via the
external IP, delete on close.  The reference speaks the GCE REST API
directly; this drives the gcloud CLI instead — same capability, no
vendored cloud SDK — and is gated on gcloud being installed+authed.

Config mapping: cfg.image = GCE image name, cfg.targets[0] optionally
"project/zone/machine-type".
"""

from __future__ import annotations

import json
import shutil
import subprocess
from typing import List, Tuple

from . import (
    Instance,
    OutputMerger,
    Pool,
    VMConfig,
    _scp,
    _ssh_args,
    _wait_ssh,
    register_backend,
)


class GceError(RuntimeError):
    pass


def _gcloud(args: List[str], timeout: float = 300.0) -> str:
    if shutil.which("gcloud") is None:
        raise GceError("gcloud CLI not installed/authenticated — the gce "
                       "backend needs it (see cloud.google.com/sdk)")
    r = subprocess.run(["gcloud", *args, "--format=json"],
                       capture_output=True, text=True, timeout=timeout)
    if r.returncode != 0:
        raise GceError(f"gcloud {' '.join(args)} failed: {r.stderr[-2000:]}")
    return r.stdout


@register_backend("gce")
class GcePool(Pool):
    def create(self, index: int) -> "GceInstance":
        return GceInstance(self.cfg, index)


class GceInstance(Instance):
    def __init__(self, cfg: VMConfig, index: int):
        self.cfg = cfg
        self.index = index
        spec = (cfg.targets[0] if cfg.targets else "//").split("/")
        self.project = spec[0] or None
        self.zone = spec[1] if len(spec) > 1 and spec[1] else \
            "us-central1-a"
        machine = spec[2] if len(spec) > 2 and spec[2] else "e2-standard-2"
        # unique across runs/pools: a leaked instance from a crashed
        # manager must not block the next create
        import secrets

        self.name = f"syzkaller-tpu-{index}-{secrets.token_hex(4)}"
        self._procs: List[subprocess.Popen] = []
        args = ["compute", "instances", "create", self.name,
                "--zone", self.zone, "--machine-type", machine,
                "--image", cfg.image]
        if self.project:
            args += ["--project", self.project]
        out = json.loads(_gcloud(args, timeout=600.0))
        try:
            try:
                self.ip = out[0]["networkInterfaces"][0][
                    "accessConfigs"][0]["natIP"]
            except (KeyError, IndexError) as e:
                raise GceError(
                    f"no external IP in create response: {out}") from e
            self.target = f"root@{self.ip}"
            _wait_ssh(self.target, 22, cfg.sshkey, f"gce {self.name}",
                      timeout=600.0)
        except BaseException:
            # never leak a billed instance the caller has no handle to
            self.close()
            raise

    def copy(self, host_src: str) -> str:
        import os

        dst = f"/{os.path.basename(host_src)}"
        _scp(host_src, self.target, dst, 22, self.cfg.sshkey)
        return dst

    def forward(self, port: int) -> str:
        from . import _local_ip

        return f"{_local_ip()}:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        merger = OutputMerger()
        proc = subprocess.Popen(
            _ssh_args(self.target, 22, self.cfg.sshkey) + [command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        merger.attach(proc.stdout)
        return merger, proc

    def close(self) -> None:
        import os
        import signal as _signal

        for proc in self._procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), _signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        try:
            args = ["compute", "instances", "delete", self.name,
                    "--zone", self.zone, "--quiet"]
            if self.project:
                args += ["--project", self.project]
            _gcloud(args, timeout=600.0)
        except GceError:
            pass  # best effort; the CI reaps leaked instances by prefix
