"""VM abstraction: disposable instances the manager boots fuzzers into.

Capability parity with reference /root/reference/vm/vm.go:48-100 and
vm/vmimpl/vmimpl.go:17-44: backend registry (`register_backend`), `Pool`
with `count`/`create`, `Instance` with copy/forward/run/close, and
`monitor_execution` — the console watchdog that turns oops lines and
output silence into crash reports (vm/vm.go:100-...).

Backends here:
  local — runs the command as a host subprocess in a scratch dir (the
          hermetic backend the reference never had; SURVEY §4 gap).
  qemu  — boots a real kernel image under qemu-system-* with a forwarded
          port and serial console (reference vm/qemu/qemu.go:29-477);
          requires an image+kernel on disk, so it is config-gated.
"""

from __future__ import annotations

import os
import shlex
import shutil
import signal
import socket
import subprocess
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..report import Report, parse as parse_report

_BACKENDS: Dict[str, Callable] = {}


def register_backend(name: str):
    def deco(cls):
        _BACKENDS[name] = cls
        return cls
    return deco


def create(cfg: "VMConfig") -> "Pool":
    if cfg.type not in _BACKENDS:
        # backends in submodules (adb, gce) register on import
        import importlib

        try:
            importlib.import_module(f".{cfg.type}", __package__)
        except ModuleNotFoundError as e:
            # only "no such backend module" is expected; a backend whose
            # own dependency is missing must surface the real error
            if e.name != f"{__package__}.{cfg.type}":
                raise
    if cfg.type not in _BACKENDS:
        raise ValueError(f"unknown VM type {cfg.type!r} "
                         f"(known: {sorted(_BACKENDS)})")
    return _BACKENDS[cfg.type](cfg)


@dataclass
class VMConfig:
    type: str = "local"
    count: int = 1
    workdir: str = ""
    # qemu-specific
    kernel: str = ""
    image: str = ""
    sshkey: str = ""
    qemu_bin: str = "qemu-system-x86_64"
    cpu: int = 2
    mem_mb: int = 2048
    qemu_args: List[str] = field(default_factory=list)
    # isolated-specific (remote physical machines over ssh)
    targets: List[str] = field(default_factory=list)  # user@host[:port]
    target_dir: str = "/tmp/syzkaller"
    target_reboot: bool = False
    # odroid-specific (dev board with hard power-cycle repair)
    console: str = ""      # host-side serial device, e.g. /dev/ttyUSB0
    power_cycle: str = ""  # host command cycling the board's hub port
    # kvm-specific (lkvm/kvmtool)
    lkvm_bin: str = "lkvm"


class Instance:
    """One booted VM. The interface every backend implements."""

    def copy(self, host_src: str) -> str:
        """Copy a file into the instance; returns the guest path."""
        raise NotImplementedError

    def forward(self, port: int) -> str:
        """Expose a host port inside the instance; returns guest addr."""
        raise NotImplementedError

    def run(self, command: str, timeout: float
            ) -> Tuple["OutputMerger", subprocess.Popen]:
        """Start command in the guest; returns the merged console+cmd
        output stream and a handle."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class Pool:
    def __init__(self, cfg: VMConfig):
        self.cfg = cfg

    @property
    def count(self) -> int:
        return self.cfg.count

    def create(self, index: int) -> Instance:
        raise NotImplementedError


class OutputMerger:
    """Accumulates interleaved console/command output with a condition
    variable so monitors can wait for new data (reference
    vm/vmimpl/merger.go)."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._cond = threading.Condition()
        self._eof = False

    def feed(self, chunk: bytes) -> None:
        with self._cond:
            self._buf.extend(chunk)
            self._cond.notify_all()

    def finish(self) -> None:
        with self._cond:
            self._eof = True
            self._cond.notify_all()

    def attach(self, stream, finish: bool = True) -> threading.Thread:
        """Pump a stream into the merger. finish=False for transient
        command streams sharing a long-lived console merger — their EOF
        must not mark the merger (and thus the instance) dead."""
        def pump():
            try:
                for line in iter(stream.readline, b""):
                    self.feed(line)
            except (OSError, ValueError):
                pass
            if finish:
                self.finish()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        return t

    def wait(self, have: int, timeout: float) -> bool:
        """Block until output grows beyond `have` bytes or EOF/timeout."""
        deadline = time.time() + timeout
        with self._cond:
            while True:
                if len(self._buf) > have or self._eof:
                    return True
                left = deadline - time.time()
                if left <= 0:
                    return False
                self._cond.wait(left)

    def size(self) -> int:
        with self._cond:
            return len(self._buf)

    def output(self, start: int = 0) -> bytes:
        with self._cond:
            return bytes(self._buf[start:])

    @property
    def eof(self) -> bool:
        with self._cond:
            return self._eof


@dataclass
class MonitorResult:
    report: Optional[Report]
    output: bytes
    timed_out: bool = False
    lost_connection: bool = False
    no_output: bool = False


def monitor_execution(merger: OutputMerger, proc,
                      timeout: float = 3600.0,
                      no_output_timeout: float = 180.0,
                      ignores: Optional[List[str]] = None,
                      stop: Optional[threading.Event] = None
                      ) -> MonitorResult:
    """Watch merged output for crashes / silence until the command exits
    (reference vm.MonitorExecution: oops regex scan + 'no output' hangs +
    'lost connection' pseudo-crashes)."""
    ignores = ignores or []
    deadline = time.time() + timeout
    start_len = merger.size()  # only this command's output matters
    last_len = start_len
    last_output_time = time.time()
    # Incremental scan: only new output (plus one line of overlap for
    # chunks split mid-line) is regex-scanned each wake; the full text is
    # re-parsed once, only when a crash is actually detected.
    overlap = 1 << 12
    while True:
        if stop is not None and stop.is_set():
            return MonitorResult(None, merger.output(start_len))
        merger.wait(last_len, timeout=5.0)
        size = merger.size()
        if size > last_len:
            window_start = max(start_len, last_len - overlap)
            window = merger.output(window_start).decode("utf-8", "replace")
            last_len = size
            last_output_time = time.time()
            if parse_report(window, ignores=ignores) is not None:
                time.sleep(1.0)  # let the rest of the report stream in
                text = merger.output(start_len).decode("utf-8", "replace")
                return MonitorResult(parse_report(text, ignores=ignores),
                                     merger.output(start_len))
        cmd_exited = proc is not None and proc.poll() is not None
        if merger.eof or cmd_exited:
            time.sleep(0.2)  # let the pump thread drain trailing output
            out = merger.output(start_len)
            # a crash report can arrive in the final flush right before
            # exit — scan it, or a real reproducer reads as lost_connection
            rep = parse_report(out.decode("utf-8", "replace"),
                               ignores=ignores)
            if rep is not None:
                return MonitorResult(rep, out)
            rc = proc.poll() if proc is not None else 0
            lost = rc not in (0, None)
            return MonitorResult(None, out, lost_connection=lost)
        if time.time() > deadline:
            return MonitorResult(None, merger.output(start_len),
                                 timed_out=True)
        if time.time() - last_output_time > no_output_timeout:
            return MonitorResult(None, merger.output(start_len),
                                 no_output=True)


# ---------------------------------------------------------------------- #
# local backend


@register_backend("local")
class LocalPool(Pool):
    def create(self, index: int) -> Instance:
        return LocalInstance(self.cfg, index)


class LocalInstance(Instance):
    """Host-subprocess 'VM': own scratch dir + process group. Hermetic
    test path for the whole manager stack."""

    def __init__(self, cfg: VMConfig, index: int):
        self.index = index
        self.dir = tempfile.mkdtemp(prefix=f"syzvm-{index}-")
        self._procs: List[subprocess.Popen] = []

    def copy(self, host_src: str) -> str:
        dst = os.path.join(self.dir, os.path.basename(host_src))
        shutil.copy2(host_src, dst)
        os.chmod(dst, 0o755)
        return dst

    def forward(self, port: int) -> str:
        return f"127.0.0.1:{port}"  # same host: no forwarding needed

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        merger = OutputMerger()
        proc = subprocess.Popen(
            command, shell=True, cwd=self.dir,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        merger.attach(proc.stdout)
        return merger, proc

    def close(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()
        shutil.rmtree(self.dir, ignore_errors=True)


# ---------------------------------------------------------------------- #
# qemu backend


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _ssh_args(target: str, port: int, key: str) -> List[str]:
    """Shared non-interactive ssh argv (qemu + isolated backends)."""
    keyargs = ["-i", key] if key else []
    return ["ssh", "-p", str(port),
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "ConnectTimeout=10",
            "-o", "BatchMode=yes", *keyargs, target]


def _scp(host_src: str, target: str, dst: str, port: int, key: str) -> None:
    keyargs = ["-i", key] if key else []
    subprocess.run(
        ["scp", "-P", str(port),
         "-o", "StrictHostKeyChecking=no",
         "-o", "UserKnownHostsFile=/dev/null",
         "-o", "ConnectTimeout=10",
         "-o", "BatchMode=yes", *keyargs,
         "-r", host_src, f"{target}:{dst}"],
        check=True, capture_output=True)


def _wait_ssh(target: str, port: int, key: str, what: str,
              timeout: float = 300.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            r = subprocess.run(_ssh_args(target, port, key) + ["true"],
                               capture_output=True, timeout=30)
            if r.returncode == 0:
                return
        except subprocess.TimeoutExpired:
            pass
        time.sleep(5)
    raise TimeoutError(f"{what}: ssh never came up")


@register_backend("qemu")
class QemuPool(Pool):
    def create(self, index: int) -> Instance:
        return QemuInstance(self.cfg, index)


class QemuInstance(Instance):
    """qemu-system VM with serial console on stdout, ssh port forward,
    and scp-based copy (reference vm/qemu/qemu.go:224-477)."""

    def __init__(self, cfg: VMConfig, index: int):
        if not cfg.kernel or not cfg.image:
            raise ValueError("qemu backend needs kernel and image paths")
        self.cfg = cfg
        self.index = index
        self.dir = tempfile.mkdtemp(prefix=f"syzqemu-{index}-")
        self.ssh_port = _free_port()
        self._fwd_ports: List[Tuple[int, int]] = []
        self.merger = OutputMerger()
        accel = (["-enable-kvm"] if os.path.exists("/dev/kvm")
                 else ["-accel", "tcg"])
        args = [
            cfg.qemu_bin,
            "-m", str(cfg.mem_mb),
            "-smp", str(cfg.cpu),
            "-kernel", cfg.kernel,
            "-append", "console=ttyS0 root=/dev/sda rw",
            "-drive", f"file={cfg.image},format=raw,if=ide",
            "-net", f"user,hostfwd=tcp:127.0.0.1:{self.ssh_port}-:22",
            "-net", "nic",
            "-nographic",
            "-no-reboot",
            *accel,
            *cfg.qemu_args,
        ]
        self.proc = subprocess.Popen(
            args, cwd=self.dir, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, start_new_session=True)
        self.merger.attach(self.proc.stdout)
        try:
            self._wait_ssh()
        except BaseException:
            # never leak a booted-but-unreachable qemu (or its tmpdir):
            # the caller has no Instance handle to close() yet
            self.close()
            raise

    def _ssh_base(self) -> List[str]:
        return _ssh_args("root@127.0.0.1", self.ssh_port, self.cfg.sshkey)

    def _wait_ssh(self, timeout: float = 300.0) -> None:
        _wait_ssh("root@127.0.0.1", self.ssh_port, self.cfg.sshkey,
                  f"qemu VM {self.index}", timeout)

    def copy(self, host_src: str) -> str:
        dst = f"/{os.path.basename(host_src)}"
        _scp(host_src, "root@127.0.0.1", dst, self.ssh_port,
             self.cfg.sshkey)
        return dst

    def forward(self, port: int) -> str:
        # reverse-forwarded into the guest when run() starts (ssh -R)
        self._fwd_ports.append((port, port))
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        fwd = []
        for hport, gport in self._fwd_ports:
            fwd += ["-R", f"{gport}:127.0.0.1:{hport}"]
        proc = subprocess.Popen(
            self._ssh_base() + fwd + [command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        # finish=False: the ssh command's EOF must not mark the shared
        # console merger dead — the instance outlives individual commands
        self.merger.attach(proc.stdout, finish=False)
        return self.merger, proc

    def close(self) -> None:
        if self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            self.proc.wait()
        shutil.rmtree(self.dir, ignore_errors=True)


@register_backend("isolated")
class IsolatedPool(Pool):
    """Remote physical machines over ssh (reference vm/isolated/
    isolated.go:22-...): no boot/teardown — each pool index is a
    long-lived host; close() only kills the running command, and repair
    optionally reboots."""

    @property
    def count(self) -> int:
        return len(self.cfg.targets)

    def create(self, index: int) -> Instance:
        return IsolatedInstance(self.cfg, index)


class IsolatedInstance(Instance):
    def __init__(self, cfg: VMConfig, index: int):
        if not cfg.targets:
            raise ValueError("isolated backend needs targets")
        self.cfg = cfg
        self.index = index
        target = cfg.targets[index % len(cfg.targets)]
        self.ssh_port = 22
        if ":" in target.rsplit("@", 1)[-1]:
            target, port = target.rsplit(":", 1)
            self.ssh_port = int(port)
        self.target = target
        self._procs: List[subprocess.Popen] = []
        # a just-rebooted host may still be coming up: wait for ssh, then
        # prepare the working dir
        _wait_ssh(self.target, self.ssh_port, cfg.sshkey,
                  f"isolated {target}", timeout=600.0)
        self._run_ssh(f"mkdir -p {shlex.quote(cfg.target_dir)}",
                      check=False)

    def _ssh_base(self) -> List[str]:
        return _ssh_args(self.target, self.ssh_port, self.cfg.sshkey)

    def _run_ssh(self, command: str, check: bool = True):
        return subprocess.run(self._ssh_base() + [command],
                              capture_output=True, timeout=60,
                              check=check)

    def copy(self, host_src: str) -> str:
        dst = f"{self.cfg.target_dir}/{os.path.basename(host_src)}"
        _scp(host_src, self.target, dst, self.ssh_port, self.cfg.sshkey)
        return dst

    def forward(self, port: int) -> str:
        # the manager is reachable from the remote host directly
        return f"{_local_ip()}:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        merger = OutputMerger()
        proc = subprocess.Popen(
            self._ssh_base() + [command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        merger.attach(proc.stdout)
        return merger, proc

    def close(self) -> None:
        # kill the REMOTE processes first (our fuzzer/executor tree keeps
        # running after the local ssh dies, like the reference notes), then
        # the local ssh clients
        try:
            self._run_ssh("pkill -KILL -f syzkaller_tpu; "
                          "pkill -KILL -f syz-executor; true", check=False)
        except Exception:
            pass
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        if self.cfg.target_reboot:
            try:
                self._run_ssh("reboot", check=False)
            except Exception:
                pass


def _local_ip() -> str:
    """Best-effort address remote targets can reach us on."""
    import socket as _socket

    s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
