"""odroid VM backend: a physical dev board with hard power-cycle repair.

Role parity with reference /root/reference/vm/odroid/odroid.go:32-...:
the board is reached over ssh (like the isolated backend), console
output is read from a USB-serial device on the host, and when the board
wedges it is repaired by power-cycling the USB hub port it hangs off.
The reference drives the hub with raw libusb CLEAR_FEATURE/SET_FEATURE
port-power requests; here the cycle shells out to a configurable command
(`power_cycle`, e.g. ``uhubctl -l 1-1 -p 4 -a cycle``) so any hub tool
or GPIO relay script works without C bindings.

Config mapping (VMConfig): targets[0] = user@board-addr, console =
serial device path (e.g. /dev/ttyUSB0), power_cycle = host command.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import time
from typing import List, Tuple

from . import (
    Instance,
    OutputMerger,
    Pool,
    VMConfig,
    _scp,
    _ssh_args,
    _wait_ssh,
    register_backend,
)


@register_backend("odroid")
class OdroidPool(Pool):
    @property
    def count(self) -> int:
        return 1  # one physical board

    def create(self, index: int) -> "OdroidInstance":
        return OdroidInstance(self.cfg, index)


class OdroidInstance(Instance):
    def __init__(self, cfg: VMConfig, index: int):
        if not cfg.targets:
            raise ValueError("odroid backend needs targets=[user@board]")
        self.cfg = cfg
        self.index = index
        self.target = cfg.targets[0]
        self.ssh_port = 22
        if ":" in self.target.rsplit("@", 1)[-1]:
            self.target, port = self.target.rsplit(":", 1)
            self.ssh_port = int(port)
        self._procs: List[subprocess.Popen] = []
        self.merger = OutputMerger()
        self._console = None
        if cfg.console:
            # Read the board's serial console from the host side.
            self._console = subprocess.Popen(
                ["cat", cfg.console], stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, start_new_session=True)
            self._procs.append(self._console)
            self.merger.attach(self._console.stdout)
        # never leak the console reader if the board won't come up: the
        # caller has no Instance handle to close() yet
        try:
            try:
                _wait_ssh(self.target, self.ssh_port, cfg.sshkey,
                          f"odroid {self.target}", timeout=120.0)
            except Exception:
                self.repair()
                _wait_ssh(self.target, self.ssh_port, cfg.sshkey,
                          f"odroid {self.target}", timeout=300.0)
            self._ssh(f"mkdir -p {shlex.quote(cfg.target_dir)}",
                      check=False)
        except BaseException:
            self.close()
            raise

    def _ssh(self, command: str, check: bool = True):
        return subprocess.run(
            _ssh_args(self.target, self.ssh_port, self.cfg.sshkey)
            + [command],
            capture_output=True, timeout=120, check=check)

    def repair(self) -> None:
        """Hard power-cycle the board via the configured hub command
        (the reference's libusb port-power dance, odroid.go ctor)."""
        cycle = getattr(self.cfg, "power_cycle", "")
        if not cycle:
            raise RuntimeError(
                "odroid board unreachable and no power_cycle configured")
        subprocess.run(cycle, shell=True, check=True, timeout=60)
        time.sleep(10)  # board boot latency before ssh probing resumes

    def copy(self, host_src: str) -> str:
        dst = os.path.join(self.cfg.target_dir,
                           os.path.basename(host_src))
        _scp(host_src, self.target, dst, self.ssh_port, self.cfg.sshkey)
        return dst

    def forward(self, port: int) -> str:
        # reverse-forwarded at run() time like the isolated backend
        self._fwd = getattr(self, "_fwd", [])
        self._fwd.append(port)
        return f"127.0.0.1:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        fwd: List[str] = []
        for p in getattr(self, "_fwd", []):
            fwd += ["-R", f"{p}:127.0.0.1:{p}"]
        proc = subprocess.Popen(
            _ssh_args(self.target, self.ssh_port, self.cfg.sshkey)
            + fwd + [command],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(proc)
        self.merger.attach(proc.stdout, finish=False)
        return self.merger, proc

    def close(self) -> None:
        # the board outlives local ssh clients: reap stale fuzzer/executor
        # trees remotely (same problem the isolated backend handles);
        # short timeout — close() must not hang on a wedged board
        try:
            subprocess.run(
                _ssh_args(self.target, self.ssh_port, self.cfg.sshkey)
                + ["pkill -f syz- || true"],
                capture_output=True, timeout=10, check=False)
        except Exception:
            pass
        for p in self._procs:
            try:
                os.killpg(os.getpgid(p.pid), 15)
            except (ProcessLookupError, PermissionError, OSError):
                pass
