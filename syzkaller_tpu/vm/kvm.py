"""kvm VM backend: lightweight VMs via lkvm (kvmtool), no qemu.

Role parity with reference /root/reference/vm/kvm/kvm.go:28-...: each
instance is an `lkvm run` process booting the configured kernel with a
9p-shared sandbox directory instead of a disk image.  There is no ssh
into the guest: the guest init script polls the shared sandbox for a
command file, executes it, and mirrors output back into the share —
copy() just drops files into the sandbox, run() writes the command file
and tails its output.  Console output is lkvm's stdout.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import time
from typing import List, Tuple

from . import Instance, OutputMerger, Pool, VMConfig, register_backend

# Guest-side init contract (mirrors the reference's sandbox script): poll
# for /host/command, run it, touch /host/done when finished.
GUEST_INIT = """#!/bin/sh
mount -t tmpfs none /tmp
while true; do
  if [ -f /host/command ]; then
    mv /host/command /host/command.running
    sh /host/command.running > /host/output 2>&1
    echo $? > /host/done
  fi
  sleep 0.1
done
"""


@register_backend("kvm")
class KvmPool(Pool):
    @property
    def count(self) -> int:
        return self.cfg.count

    def create(self, index: int) -> "KvmInstance":
        return KvmInstance(self.cfg, index)


class KvmInstance(Instance):
    def __init__(self, cfg: VMConfig, index: int):
        if not cfg.kernel:
            raise ValueError("kvm backend needs a kernel image")
        if cfg.qemu_bin not in ("", "qemu-system-x86_64") and \
                cfg.lkvm_bin == "lkvm":
            # old configs pointed qemu_bin at the kvmtool binary; fail
            # loudly instead of silently execing bare "lkvm" from PATH
            raise ValueError(
                "kvm backend: set lkvm_bin (qemu_bin is ignored here)")
        self.cfg = cfg
        self.index = index
        self.sandbox = os.path.join(cfg.workdir or "/tmp",
                                    f"kvm-sandbox-{index}")
        os.makedirs(self.sandbox, exist_ok=True)
        # the sandbox path is reused across instance recreations: drop any
        # stale control files or the fresh guest executes last session's
        # command before the new package is even copied in
        for stale in ("command", "command.running", "done", "output"):
            p = os.path.join(self.sandbox, stale)
            if os.path.exists(p):
                os.unlink(p)
        init = os.path.join(self.sandbox, "init.sh")
        with open(init, "w") as f:
            f.write(GUEST_INIT)
        os.chmod(init, 0o755)
        cmd = [
            cfg.lkvm_bin, "run",
            "--name", f"syz-{index}",
            "-k", cfg.kernel,
            "-c", str(cfg.cpu),
            "-m", str(cfg.mem_mb),
            "--9p", f"{self.sandbox},host",
            "--network", "mode=user",
            # init=/host/init.sh is the command channel and must survive;
            # qemu_args are *extra* kernel params, same meaning as in the
            # qemu backend.
            "--params", " ".join(["init=/host/init.sh", *cfg.qemu_args]),
        ]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs: List[subprocess.Popen] = [self.proc]
        self.merger = OutputMerger()
        self.merger.attach(self.proc.stdout)
        # watch the boot briefly: exit on first console output (healthy)
        # or on early death; don't serially burn the full window per VM
        deadline = time.time() + 10
        while time.time() < deadline:
            if self.proc.poll() is not None:
                out = self.merger.output()[:4096]
                self.close()
                raise RuntimeError(f"lkvm exited at boot: {out!r}")
            if self.merger.size() > 0:
                break
            time.sleep(0.2)

    def copy(self, host_src: str) -> str:
        import shutil

        dst = os.path.join(self.sandbox, os.path.basename(host_src))
        shutil.copy(host_src, dst)
        os.chmod(dst, 0o755)
        return f"/host/{os.path.basename(host_src)}"

    def forward(self, port: int) -> str:
        # lkvm user-mode networking exposes the host at the gateway addr
        # (reference kvm.go hostAddr 192.168.33.1).
        return f"192.168.33.1:{port}"

    def run(self, command: str, timeout: float
            ) -> Tuple[OutputMerger, subprocess.Popen]:
        # One in-flight guest command per instance: the 9p control files
        # (command/output/done) are shared state, so a second run() while
        # the previous tail is still alive would interleave output and
        # exit status.  Reap a finished tail; refuse while one is running.
        prev = getattr(self, "_tail", None)
        if prev is not None:
            if prev.poll() is None:
                raise RuntimeError(
                    "kvm instance busy: previous run() still in flight")
            self._tail = None
        for leftover in ("done", "output", "command.running"):
            p = os.path.join(self.sandbox, leftover)
            if os.path.exists(p):
                os.unlink(p)
        cmdfile = os.path.join(self.sandbox, "command")
        with open(cmdfile + ".tmp", "w") as f:
            f.write(command + "\n")
        os.rename(cmdfile + ".tmp", cmdfile)
        outpath = os.path.join(self.sandbox, "output")
        # tail the mirrored output; terminates when done appears or on kill
        tail = subprocess.Popen(
            ["sh", "-c",
             f"touch {shlex.quote(outpath)}; "
             f"tail -f {shlex.quote(outpath)} & TP=$!; "
             f"while [ ! -f {shlex.quote(self.sandbox)}/done ]; "
             # grace period after done appears: let tail drain the final
             # 9p-written chunk (a crash report's tail) before the kill;
             # then propagate the guest command's exit status so the
             # monitor's lost-connection detection works like ssh's
             "do sleep 0.2; done; sleep 0.5; kill $TP; "
             f"exit $(cat {shlex.quote(self.sandbox)}/done)"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)
        self._procs.append(tail)
        self._tail = tail
        # finish=False: a command's end must not mark the shared console
        # merger (and thus the instance) dead.
        self.merger.attach(tail.stdout, finish=False)
        return self.merger, tail

    def close(self) -> None:
        for p in self._procs:
            try:
                os.killpg(os.getpgid(p.pid), 15)
            except (ProcessLookupError, PermissionError, OSError):
                pass
