"""Crash-report recognition, title extraction, and dedup.

Capability parity with reference /root/reference/pkg/report/report.go:20-465:
a table of oops families (KASAN, BUG, WARNING, lockdep, rcu stalls, GPF,
panics, kmemleak, ...) each with title-extraction patterns; `contains_crash`
is the console-monitor hot predicate; `parse` finds the first crash, formats
a canonical dedup title, and slices the report text out of the console
stream. `Symbolizer` (report/symbolize.py) rewrites stack traces via
addr2line.

Pattern syntax: Python regexes with placeholder macros expanded before
compilation — {{FUNC}} (captures the function name), {{PC}}, {{ADDR}},
{{SRC}} (captures file:line). Title formats refer to capture groups as {0},
{1}, ... in pattern-capture order.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

_MACROS = {
    "{{FUNC}}": r"([a-zA-Z0-9_]+)(?:\.(?:constprop|isra|part)\.[0-9]+)?"
                r"(?:\+0x[0-9a-f]+(?:/0x[0-9a-f]+)?)?",
    "{{PC}}": r"(?:\[<)?(?:0x)?[0-9a-f]{8,16}(?:>\])?",
    "{{ADDR}}": r"(?:0x)?[0-9a-f]{8,16}",
    "{{SRC}}": r"([a-zA-Z0-9_\-./]+\.[chS]:[0-9]+)",
}


def _compile(pattern: str) -> re.Pattern:
    for macro, repl in _MACROS.items():
        pattern = pattern.replace(macro, repl)
    return re.compile(pattern)


@dataclass
class _Format:
    pattern: re.Pattern
    title: str  # with {0}-style group refs


@dataclass
class Oops:
    header: str
    formats: List[_Format]
    suppressions: List[re.Pattern] = field(default_factory=list)


def _fmt(header: str, entries: Sequence[Tuple[str, str]],
         suppressions: Sequence[str] = ()) -> Oops:
    return Oops(header,
                [_Format(_compile(p), t) for p, t in entries],
                [_compile(s) for s in suppressions])


# Family table. Order matters: first matching header wins; within a family
# the first matching (usually most specific) format names the crash.
OOPSES: List[Oops] = [
    _fmt("BUG:", [
        (r"BUG: KASAN: ([a-z\-]+) in {{FUNC}}(?:.*\n)+?.*(Read|Write) of size ([0-9]+)",
         "KASAN: {0} {2} in {1}"),
        (r"BUG: KASAN: ([a-z\-]+) on address(?:.*\n)+?.*(Read|Write) of size ([0-9]+)",
         "KASAN: {0} {1} of size {2}"),
        # modern KASAN has no Read/Write line for some kinds; kind may be
        # multi-word ("double-free or invalid-free")
        (r"BUG: KASAN: ([a-z\- ]+?) in {{FUNC}}", "KASAN: {0} in {1}"),
        (r"BUG: KASAN: (.*)", "KASAN: {0}"),
        (r"BUG: KCSAN: ([a-z\-]+) in {{FUNC}}", "KCSAN: {0} in {1}"),
        (r"BUG: KMSAN: ([a-z\-]+) in {{FUNC}}", "KMSAN: {0} in {1}"),
        (r"BUG: unable to handle kernel paging request(?:.*\n)+?.*IP: (?:{{PC}} +)?{{FUNC}}",
         "BUG: unable to handle kernel paging request in {0}"),
        (r"BUG: unable to handle kernel NULL pointer dereference(?:.*\n)+?.*IP: (?:{{PC}} +)?{{FUNC}}",
         "BUG: unable to handle kernel NULL pointer dereference in {0}"),
        # post-4.19 page-fault report format
        (r"BUG: unable to handle page fault for address:(?:.*\n)+?"
         r".*RIP: [0-9]+:{{FUNC}}",
         "BUG: unable to handle kernel paging request in {0}"),
        (r"BUG: kernel NULL pointer dereference, address:(?:.*\n)+?"
         r".*RIP: [0-9]+:{{FUNC}}",
         "BUG: unable to handle kernel NULL pointer dereference in {0}"),
        (r"BUG: stack guard page was hit(?:.*\n)+?.*RIP: [0-9]+:{{FUNC}}",
         "BUG: stack guard page was hit in {0}"),
        (r"BUG: sleeping function called from invalid context at {{SRC}}",
         "BUG: sleeping function called from invalid context at {0}"),
        (r"BUG: workqueue lockup", "BUG: workqueue lockup"),
        (r"BUG: scheduling while atomic", "BUG: scheduling while atomic"),
        (r"BUG: corrupted list in {{FUNC}}", "BUG: corrupted list in {0}"),
        (r"BUG: spinlock lockup suspected", "BUG: spinlock lockup suspected"),
        (r"BUG: spinlock recursion", "BUG: spinlock recursion"),
        (r"BUG: spinlock bad magic", "BUG: spinlock bad magic"),
        (r"BUG: soft lockup.*(?:\n.*)*?RIP: [0-9]+:{{FUNC}}",
         "BUG: soft lockup in {0}"),
        (r"BUG: soft lockup", "BUG: soft lockup"),
        (r"BUG: .*still has locks held!(?:.*\n)+?.*{{PC}} +{{FUNC}}",
         "BUG: still has locks held in {0}"),
        (r"BUG: bad unlock balance detected!(?:.*\n)+?.*{{PC}} +{{FUNC}}",
         "BUG: bad unlock balance in {0}"),
        (r"BUG: held lock freed!(?:.*\n)+?.*{{PC}} +{{FUNC}}",
         "BUG: held lock freed in {0}"),
        (r"BUG: Bad rss-counter state", "BUG: Bad rss-counter state"),
        (r"BUG: non-zero nr_ptes on freeing mm",
         "BUG: non-zero nr_ptes on freeing mm"),
        (r"BUG: non-zero nr_pmds on freeing mm",
         "BUG: non-zero nr_pmds on freeing mm"),
        (r"BUG: Dentry .* still in use \([0-9]+\) \[unmount of ([^\]]+)\]",
         "BUG: Dentry still in use [unmount of {0}]"),
        (r"BUG: Bad page state", "BUG: Bad page state"),
        (r"BUG: unable to handle kernel",
         "BUG: unable to handle kernel"),
        (r"BUG: (.*)", "BUG: {0}"),
    ], suppressions=[r"BUG: using __this_cpu_"]),
    _fmt("WARNING:", [
        (r"WARNING: possible circular locking dependency detected(?:.*\n)+?"
         r".*is trying to acquire lock(?:.*\n)+?.*at: (?:{{PC}} +)?{{FUNC}}",
         "possible deadlock in {0}"),
        (r"WARNING: possible irq lock inversion dependency detected(?:.*\n)+?"
         r".*just changed the state of lock(?:.*\n)+?.*at: (?:{{PC}} +)?{{FUNC}}",
         "possible deadlock in {0}"),
        (r"WARNING: SOFTIRQ-safe -> SOFTIRQ-unsafe lock order detected"
         r"(?:.*\n)+?.*is trying to acquire(?:.*\n)+?.*at: (?:{{PC}} +)?{{FUNC}}",
         "possible deadlock in {0}"),
        (r"WARNING: possible recursive locking detected(?:.*\n)+?"
         r".*is trying to acquire lock(?:.*\n)+?.*at: (?:{{PC}} +)?{{FUNC}}",
         "possible deadlock in {0}"),
        (r"WARNING: inconsistent lock state(?:.*\n)+?.*takes(?:.*\n)+?"
         r".*at: (?:{{PC}} +)?{{FUNC}}", "inconsistent lock state in {0}"),
        (r"WARNING: suspicious RCU usage(?:.*\n)+?.*?{{SRC}}",
         "suspicious RCU usage at {0}"),
        (r"WARNING: kernel stack regs at [0-9a-f]+ in [^ ]* has bad "
         r"'([^']+)' value", "WARNING: kernel stack regs has bad '{0}' value"),
        (r"WARNING: kernel stack frame pointer at [0-9a-f]+ in [^ ]* has "
         r"bad value", "WARNING: kernel stack frame pointer has bad value"),
        (r"WARNING: .* at {{SRC}} {{FUNC}}", "WARNING in {1}"),
        (r"WARNING: (.*)", "WARNING: {0}"),
    ], suppressions=[r"WARNING: /etc/ssh/moduli does not exist"]),
    _fmt("INFO:", [
        (r"INFO: possible circular locking dependency detected(?:.*\n)+?"
         r".*is trying to acquire lock(?:.*\n)+?.*at: (?:{{PC}} +)?{{FUNC}}",
         "possible deadlock in {0}"),
        (r"INFO: rcu_(?:preempt|sched|bh) (?:self-)?detected"
         r"(?: expedited)? stalls?.*(?:\n.*)*?RIP: [0-9]+:{{FUNC}}",
         "INFO: rcu detected stall in {0}"),
        (r"INFO: rcu_(?:preempt|sched|bh) (?:self-)?detected"
         r"(?: expedited)? stalls?", "INFO: rcu detected stall"),
        (r"INFO: task .* blocked for more than [0-9]+ seconds",
         "INFO: task hung"),
        (r"INFO: suspicious RCU usage(?:.*\n)+?.*?{{SRC}}",
         "suspicious RCU usage at {0}"),
        (r"INFO: (.*)", "INFO: {0}"),
    ], suppressions=[
        r"INFO: lockdep is turned off",
        r"INFO: Stall ended before state dump start",
        r"INFO: NMI handler .* took too long to run",
    ]),
    _fmt("Unable to handle kernel paging request", [
        (r"Unable to handle kernel paging request(?:.*\n)+?.*PC is at {{FUNC}}",
         "unable to handle kernel paging request in {0}"),
        (r"Unable to handle kernel paging request",
         "unable to handle kernel paging request"),
    ]),
    # ":" (classic) and "," (modern "probably for non-canonical address")
    # headers; both miss the userspace trap line "traps: ... general
    # protection fault ip:..." on purpose
    _fmt("general protection fault:", [
        (r"general protection fault:(?:.*\n)+?.*RIP: [0-9]+:(?:{{PC}} +{{PC}} +)?{{FUNC}}",
         "general protection fault in {0}"),
        (r"general protection fault:", "general protection fault"),
    ]),
    _fmt("general protection fault,", [
        (r"general protection fault,.*(?:\n.*)*?RIP: [0-9]+:{{FUNC}}",
         "general protection fault in {0}"),
        (r"general protection fault,", "general protection fault"),
    ]),
    _fmt("double fault:", [
        (r"double fault:(?:.*\n)+?.*RIP: [0-9]+:{{FUNC}}",
         "double fault in {0}"),
        (r"double fault:", "double fault"),
    ]),
    _fmt("stack segment:", [
        (r"stack segment:(?:.*\n)+?.*RIP: [0-9]+:{{FUNC}}",
         "stack segment fault in {0}"),
        (r"stack segment:", "stack segment fault"),
    ]),
    _fmt("Kernel stack overflow", [
        (r"Kernel stack overflow", "kernel stack overflow"),
    ]),
    _fmt("Kernel panic", [
        (r"Kernel panic - not syncing: Attempted to kill init!",
         "kernel panic: Attempted to kill init!"),
        (r"Kernel panic - not syncing: Couldn't open N_TTY ldisc",
         "kernel panic: Couldn't open N_TTY ldisc"),
        (r"Kernel panic - not syncing: (.*)", "kernel panic: {0}"),
    ]),
    _fmt("kernel BUG", [
        (r"kernel BUG at {{SRC}}", "kernel BUG at {0}"),
        (r"kernel BUG (.*)", "kernel BUG {0}"),
    ]),
    _fmt("Kernel BUG", [
        (r"Kernel BUG (.*)", "kernel BUG {0}"),
    ]),
    _fmt("BUG kmalloc-", [
        (r"BUG kmalloc-.*: Object already free", "BUG: Object already free"),
    ]),
    _fmt("divide error:", [
        (r"divide error: (?:.*\n)+?.*RIP: [0-9]+:(?:{{PC}} +{{PC}} +)?{{FUNC}}",
         "divide error in {0}"),
        (r"divide error:", "divide error"),
    ]),
    _fmt("invalid opcode:", [
        (r"invalid opcode: (?:.*\n)+?.*RIP: [0-9]+:(?:{{PC}} +{{PC}} +)?{{FUNC}}",
         "invalid opcode in {0}"),
        (r"invalid opcode:", "invalid opcode"),
    ]),
    _fmt("unreferenced object", [
        (r"unreferenced object {{ADDR}} \(size ([0-9]+)\):(?:.*\n)+?"
         r".*backtrace:(?:.*\n)+?.*{{PC}}.*\n.*{{PC}}.*\n.*{{PC}} {{FUNC}}",
         "memory leak in {1} (size {0})"),
        (r"unreferenced object", "memory leak"),
    ]),
    _fmt("UBSAN:", [
        (r"UBSAN: (.*)", "UBSAN: {0}"),
    ]),
    _fmt("unregister_netdevice: waiting for", [
        (r"unregister_netdevice: waiting for (.*) to become free",
         "unregister_netdevice: waiting for DEV to become free"),
    ]),
]

# "no output" / lost-connection pseudo-crashes are produced by the VM
# monitor, not by this parser (reference vm/vm.go:100-...).

_CONSOLE_PREFIX = re.compile(
    r"^(?:<[0-9]+>)?(?:\[[ 0-9.]+\]\s?)?")


@dataclass
class Report:
    title: str
    report: str = ""     # the crash text slice
    output: str = ""     # full console output it was found in
    start_pos: int = 0
    end_pos: int = 0
    corrupted: bool = False
    oops_header: str = ""


def _strip_line(line: str) -> str:
    return _CONSOLE_PREFIX.sub("", line)


def contains_crash(output: str,
                   ignores: Sequence[str] = ()) -> bool:
    """The console-monitor hot predicate (reference ContainsCrash)."""
    ign = [re.compile(i) for i in ignores]
    return _find(output, ign) is not None


def _suppressed(oops: Oops, line: str,
                ignores: Sequence[re.Pattern]) -> bool:
    return (any(s.search(line) for s in oops.suppressions)
            or any(i.search(line) for i in ignores))


def _find(output: str, ignores: Sequence[re.Pattern]
          ) -> Optional[Tuple[int, Oops, str]]:
    pos = 0
    for raw in output.splitlines(keepends=True):
        line = _strip_line(raw.rstrip("\n"))
        for oops in OOPSES:
            if oops.header in line and not _suppressed(oops, line, ignores):
                return pos, oops, line
        pos += len(raw)
    return None


def parse(output: str, ignores: Sequence[str] = ()) -> Optional[Report]:
    """Find the first crash in console output; extract canonical title and
    the report slice (reference Parse, report.go:369-465)."""
    ign = [re.compile(i) for i in ignores]
    found = _find(output, ign)
    if found is None:
        return None
    start, oops, _line = found
    # report slice: from the oops line up to the next UNRELATED oops header
    # (bounded window otherwise) — multi-line title formats that scan for a
    # RIP line must never read a later crash's registers
    end = min(len(output), start + (64 << 10))
    first_line_end = output.find("\n", start)
    if 0 <= first_line_end < end:
        nxt = _find(output[first_line_end:end], ign)
        if nxt is not None:
            end = first_line_end + nxt[0]
    body = "\n".join(_strip_line(ln)
                     for ln in output[start:end].splitlines())
    title = None
    for f in oops.formats:
        m = f.pattern.search(body)
        if m:
            title = f.title.format(*m.groups())
            break
    corrupted = title is None
    if title is None:
        title = _strip_line(body.splitlines()[0])[:120] if body else oops.header
    return Report(title=title, report=body, output=output,
                  start_pos=start, end_pos=end, corrupted=corrupted,
                  oops_header=oops.header)


def extract_guilty_file(report: str) -> Optional[str]:
    """First source file in the stack trace that is not a generic helper
    (reference pkg/report/guilty.go)."""
    generic = re.compile(
        r"^(?:mm/kasan/|mm/slab|mm/slub|kernel/locking/|lib/|"
        r"arch/x86/(?:lib|mm)/|include/)")
    for m in re.finditer(r"([a-z0-9_\-./]+\.[chS]):[0-9]+", report):
        f = m.group(1)
        if not generic.search(f):
            return f
    return None
