"""Stack-trace symbolization via addr2line (reference pkg/symbolizer +
report.go:567-659 Symbolize: rewrite `func+0xOFF/0xSIZE` frames with
file:line from vmlinux)."""

from __future__ import annotations

import re
import subprocess
from typing import Dict, List, Optional, Tuple

_FRAME = re.compile(
    r"(?P<pre>.*?\[<(?P<pc>[0-9a-f]+)>\]\s+)?"
    r"(?P<func>[a-zA-Z0-9_]+)\+(?P<off>0x[0-9a-f]+)/(?P<size>0x[0-9a-f]+)")


class Symbolizer:
    """Batch addr2line over a vmlinux image. Symbol table comes from `nm`
    once; each frame's PC = sym_addr + offset."""

    def __init__(self, vmlinux: str, addr2line: str = "addr2line",
                 nm: str = "nm"):
        self.vmlinux = vmlinux
        self.addr2line = addr2line
        self.nm = nm
        self._symbols: Optional[Dict[str, List[Tuple[int, int]]]] = None

    def _load_symbols(self) -> Dict[str, List[Tuple[int, int]]]:
        if self._symbols is not None:
            return self._symbols
        out = subprocess.run([self.nm, "-nS", self.vmlinux],
                             capture_output=True, text=True, check=True)
        syms: Dict[str, List[Tuple[int, int]]] = {}
        for line in out.stdout.splitlines():
            parts = line.split()
            if len(parts) == 4 and parts[2].lower() in ("t", "w"):
                addr, size, _typ, name = parts
                syms.setdefault(name, []).append(
                    (int(addr, 16), int(size, 16)))
        self._symbols = syms
        return syms

    def _resolve(self, pcs: List[int]) -> List[str]:
        """Resolve PCs to file:line, feeding addresses via stdin (argv
        would hit ARG_MAX for the coverage-report-sized batches the /cover
        page sends).  Results are memoized per PC."""
        if not hasattr(self, "_resolve_cache"):
            self._resolve_cache: Dict[int, str] = {}
        todo = [pc for pc in pcs if pc not in self._resolve_cache]
        if todo:
            proc = subprocess.run(
                [self.addr2line, "-afi", "-e", self.vmlinux],
                input="".join(f"{pc:#x}\n" for pc in todo),
                capture_output=True, text=True, check=True)
            locs: List[str] = []
            cur: List[str] = []
            for line in proc.stdout.splitlines():
                if line.startswith("0x"):
                    if cur:
                        locs.append(cur[-1])
                    cur = []
                elif ":" in line:
                    cur.append(line.strip())
            if cur:
                locs.append(cur[-1])
            for pc, loc in zip(todo, locs):
                self._resolve_cache[pc] = loc
        return [self._resolve_cache.get(pc, "??:0") for pc in pcs]

    def symbolize_report(self, report: str) -> str:
        """Append file:line to every frame whose symbol resolves."""
        syms = self._load_symbols()
        frames = []
        for m in _FRAME.finditer(report):
            cands = syms.get(m.group("func"))
            if not cands:
                continue
            off = int(m.group("off"), 16)
            size = int(m.group("size"), 16)
            for addr, ssize in cands:
                if ssize == size and off < ssize:
                    frames.append((m, addr + off))
                    break
        if not frames:
            return report
        locs = self._resolve([pc for _, pc in frames])
        out = report
        # substitute back-to-front so match positions stay valid
        for (m, _pc), loc in reversed(list(zip(frames, locs))):
            ins = f" {loc}"
            if loc and loc not in ("??:0", "??:?"):
                out = out[: m.end()] + ins + out[m.end():]
        return out
