"""Hub: cross-manager corpus + reproducer exchange.

Role parity with the reference's syz-hub (reference: /root/reference/
syz-hub/hub.go:68-117 Connect/Sync RPC; syz-hub/state/state.go:54-356
per-manager on-disk state with delta-sync sequence numbers, call-set
filtering, More backpressure, and corpus purge).  Differences from the
reference are deliberate: programs travel as text (JSON frames over the
same RPC layer the manager<->fuzzer protocol uses), and per-record
sequence numbers live in one JSON index per database instead of inside
the db records.

In the TPU deployment picture this is the DCN tier: within a pod, signal
bitsets union over ICI collectives (parallel/collective.py); across pods
and between independent manager hosts, corpus deltas flow through a hub
exactly like the reference's multi-manager federation (SURVEY.md §2.6).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..db import DB
from ..manager.rpc import RpcClient, RpcServer
from ..prog.encoding import call_set
from ..utils.hash import hash_str

MAX_SYNC_RECORDS = 1000  # More-backpressure threshold (state.go:292)


class AuthError(RuntimeError):
    pass


class _SeqDB:
    """A corpus DB plus a persisted sig->seq index (the reference embeds
    seq in db records; we keep a sidecar JSON)."""

    def __init__(self, path: str):
        self.db = DB.open(path)
        self.seq_path = path + ".seq"
        self.seqs: Dict[str, int] = {}
        if os.path.exists(self.seq_path):
            try:
                self.seqs = {k: int(v) for k, v in json.loads(
                    open(self.seq_path).read()).items()}
            except (ValueError, OSError):
                self.seqs = {}
        # drop seq entries for records that no longer exist; records whose
        # sidecar entry was lost (crash between db flush and seq replace)
        # get max_seq+1 so `cursor >= seq` filters still deliver them
        have = {k.decode() for k, _ in self.db.items()}
        self.seqs = {k: v for k, v in self.seqs.items() if k in have}
        recovered = have - self.seqs.keys()
        if recovered:
            seq = max(self.seqs.values(), default=0) + 1
            for k in recovered:
                self.seqs[k] = seq

    @property
    def max_seq(self) -> int:
        return max(self.seqs.values(), default=0)

    def save(self, sig: str, value: bytes, seq: int) -> None:
        self.db.save(sig.encode(), value)
        self.seqs[sig] = seq

    def delete(self, sig: str) -> None:
        self.db.delete(sig.encode())
        self.seqs.pop(sig, None)

    def __contains__(self, sig: str) -> bool:
        return sig.encode() in self.db

    def get(self, sig: str) -> Optional[bytes]:
        return self.db.get(sig.encode())

    def sigs(self) -> List[str]:
        return list(self.seqs)

    def flush(self) -> None:
        self.db.flush()
        tmp = self.seq_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.seqs, f)
        os.replace(tmp, self.seq_path)

    def close(self) -> None:
        self.db.close()


class _HubManager:
    """Per-manager hub-side state (state.go:34-50)."""

    def __init__(self, dir_: str, name: str):
        self.name = name
        self.dir = dir_
        os.makedirs(dir_, exist_ok=True)
        self.corpus = _SeqDB(os.path.join(dir_, "corpus.db"))
        self.seq_file = os.path.join(dir_, "seq")
        self.repro_seq_file = os.path.join(dir_, "repro.seq")
        self.corpus_seq = _load_seq(self.seq_file)
        self.repro_seq = _load_seq(self.repro_seq_file)
        self.calls: Set[str] = set()
        # persisted: after a restart a manager must still never get its own
        # reproducer delivered back to it
        self._own_repros_file = os.path.join(dir_, "own.repros")
        self.own_repros: Set[str] = set()
        try:
            self.own_repros = set(json.loads(
                open(self._own_repros_file).read()))
        except (OSError, ValueError):
            pass
        self.connected = 0.0
        # running totals for the hub status page / tests
        self.added = self.deleted = self.new = 0
        self.sent_repros = self.recv_repros = 0

    def save_seqs(self) -> None:
        _save_seq(self.seq_file, self.corpus_seq)
        _save_seq(self.repro_seq_file, self.repro_seq)


def _load_seq(path: str) -> int:
    try:
        return int(open(path).read().strip())
    except (OSError, ValueError):
        return 0


def _save_seq(path: str, seq: int) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(seq))
    os.replace(tmp, path)


class HubState:
    """All hub state, persisted under `dir` (state.go:54-139)."""

    def __init__(self, dir_: str):
        self.dir = dir_
        os.makedirs(dir_, exist_ok=True)
        self.corpus = _SeqDB(os.path.join(dir_, "corpus.db"))
        self.repros = _SeqDB(os.path.join(dir_, "repro.db"))
        # the global counters are persisted independently of the records:
        # deriving them from surviving record seqs alone could regress the
        # counter below a manager's persisted cursor after deletions +
        # restart, permanently hiding newer inputs from that manager
        self._corpus_seq_file = os.path.join(dir_, "corpus.seq")
        self._repro_seq_file = os.path.join(dir_, "repro.seq")
        self.corpus_seq = max(self.corpus.max_seq,
                              _load_seq(self._corpus_seq_file))
        self.repro_seq = max(self.repros.max_seq,
                             _load_seq(self._repro_seq_file))
        self.managers: Dict[str, _HubManager] = {}
        mdir = os.path.join(dir_, "manager")
        os.makedirs(mdir, exist_ok=True)
        for name in sorted(os.listdir(mdir)):
            self.managers[name] = _HubManager(os.path.join(mdir, name), name)
        self.purge_corpus()

    # ---- lifecycle ----

    def _manager(self, name: str) -> _HubManager:
        if name not in self.managers:
            self.managers[name] = _HubManager(
                os.path.join(self.dir, "manager", name), name)
        return self.managers[name]

    def connect(self, name: str, fresh: bool, calls: Sequence[str],
                corpus: Sequence[str]) -> None:
        """(Re)register a manager; `fresh` resets its delta cursor so it
        receives the whole hub corpus again (state.go:141-173)."""
        mgr = self._manager(name)
        mgr.connected = time.time()
        if fresh:
            mgr.corpus_seq = 0
            mgr.repro_seq = 0
        mgr.save_seqs()
        mgr.calls = set(calls)
        # reset the manager's mirrored corpus to exactly what it declared
        mgr.corpus.close()
        for suffix in ("", ".seq"):
            p = os.path.join(mgr.dir, "corpus.db" + suffix)
            if os.path.exists(p):
                os.remove(p)
        mgr.corpus = _SeqDB(os.path.join(mgr.dir, "corpus.db"))
        self._add_inputs(mgr, corpus)
        self.purge_corpus()

    def sync(self, name: str, add: Sequence[str], del_: Sequence[str]
             ) -> Tuple[List[str], int]:
        """One delta exchange; returns (progs_for_manager, more_pending)
        (state.go:175-196)."""
        mgr = self.managers.get(name)
        if mgr is None or not mgr.connected:
            raise RuntimeError(f"unconnected manager {name!r}")
        if del_:
            for sig in del_:
                mgr.corpus.delete(sig)
            mgr.corpus.flush()
            self.purge_corpus()
        self._add_inputs(mgr, add)
        progs, more = self._pending_inputs(mgr)
        mgr.added += len(add)
        mgr.deleted += len(del_)
        mgr.new += len(progs)
        return progs, more

    # ---- repro exchange (state.go:197-264) ----

    def add_repro(self, name: str, repro: str) -> None:
        mgr = self.managers.get(name)
        if mgr is None or not mgr.connected:
            raise RuntimeError(f"unconnected manager {name!r}")
        if not call_set(repro):
            return
        sig = hash_str(repro.encode())
        if sig in self.repros:
            return
        mgr.own_repros.add(sig)
        tmp = mgr._own_repros_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sorted(mgr.own_repros), f)
        os.replace(tmp, mgr._own_repros_file)
        mgr.sent_repros += 1
        if mgr.repro_seq == self.repro_seq:
            mgr.repro_seq += 1
            _save_seq(mgr.repro_seq_file, mgr.repro_seq)
        self.repro_seq += 1
        self.repros.save(sig, repro.encode(), self.repro_seq)
        self.repros.flush()
        _save_seq(self._repro_seq_file, self.repro_seq)

    def pending_repro(self, name: str) -> Optional[str]:
        mgr = self.managers.get(name)
        if mgr is None or not mgr.connected:
            raise RuntimeError(f"unconnected manager {name!r}")
        if mgr.repro_seq == self.repro_seq:
            return None
        best_sig, best_seq = None, None
        for sig, seq in self.repros.seqs.items():
            if mgr.repro_seq >= seq or sig in mgr.own_repros:
                continue
            val = self.repros.get(sig)
            if val is None:
                continue
            if not mgr.calls.issuperset(call_set(val.decode())):
                continue
            if best_seq is None or seq < best_seq:
                best_sig, best_seq = sig, seq
        if best_sig is None:
            mgr.repro_seq = self.repro_seq
            _save_seq(mgr.repro_seq_file, mgr.repro_seq)
            return None
        mgr.recv_repros += 1
        mgr.repro_seq = best_seq
        _save_seq(mgr.repro_seq_file, mgr.repro_seq)
        return self.repros.get(best_sig).decode()

    # ---- internals ----

    def _add_inputs(self, mgr: _HubManager, inputs: Sequence[str]) -> None:
        if not inputs:
            return
        for text in inputs:
            if not call_set(text):
                continue
            sig = hash_str(text.encode())
            mgr.corpus.save(sig, b"", 0)
            if sig not in self.corpus:
                # per-record seqs (not per-batch): a 100k-program connect
                # must still page out MAX_SYNC_RECORDS at a time
                self.corpus_seq += 1
                self.corpus.save(sig, text.encode(), self.corpus_seq)
        mgr.corpus.flush()
        self.corpus.flush()
        _save_seq(self._corpus_seq_file, self.corpus_seq)

    def _pending_inputs(self, mgr: _HubManager) -> Tuple[List[str], int]:
        """Deltas since the manager's cursor, call-filtered, capped at
        MAX_SYNC_RECORDS with a More count (state.go:265-309)."""
        if mgr.corpus_seq == self.corpus_seq:
            return [], 0
        records: List[Tuple[int, str, str]] = []  # (seq, sig, text)
        for sig, seq in self.corpus.seqs.items():
            if mgr.corpus_seq >= seq or sig in mgr.corpus:
                continue
            val = self.corpus.get(sig)
            if val is None:
                continue
            text = val.decode()
            if not mgr.calls.issuperset(call_set(text)):
                continue
            records.append((seq, sig, text))
        max_seq = self.corpus_seq
        more = 0
        if len(records) > MAX_SYNC_RECORDS:
            records.sort()
            # cut after MAX records, extended through the last included
            # record's whole seq group so the cursor stays consistent
            cut = MAX_SYNC_RECORDS
            last_seq = records[cut - 1][0]
            while cut < len(records) and records[cut][0] == last_seq:
                cut += 1
            more = len(records) - cut
            records = records[:cut]
            max_seq = last_seq
        mgr.corpus_seq = max_seq
        _save_seq(mgr.seq_file, mgr.corpus_seq)
        return [text for _, _, text in records], more

    def purge_corpus(self) -> None:
        """Drop hub-corpus records no connected manager mirrors
        (state.go:338-354)."""
        used: Set[str] = set()
        for mgr in self.managers.values():
            used.update(mgr.corpus.sigs())
        for sig in list(self.corpus.sigs()):
            if sig not in used:
                self.corpus.delete(sig)
        self.corpus.flush()

    def close(self) -> None:
        self.corpus.close()
        self.repros.close()
        for mgr in self.managers.values():
            mgr.corpus.close()


@dataclass
class HubConfig:
    workdir: str
    rpc: str = "127.0.0.1:0"
    clients: Dict[str, str] = field(default_factory=dict)  # name -> key


class Hub:
    """The hub service: auth + locking around HubState, exposed over the
    shared RPC layer (hub.go:31-124)."""

    def __init__(self, cfg: HubConfig):
        self.cfg = cfg
        self.state = HubState(cfg.workdir)
        self.lock = threading.Lock()
        host, port = cfg.rpc.rsplit(":", 1)
        self._server = RpcServer(_HubHandler(self), host, int(port))
        self.addr = self._server.addr

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()
        with self.lock:
            self.state.close()

    def auth(self, client: str, key: str, manager: str) -> str:
        want = self.cfg.clients.get(client)
        if want is None or want != key:
            raise AuthError(f"unauthorized client {client!r}")
        name = client
        if manager:
            # sub-managers: "client-manager", like the reference's
            # client/manager split (hub.go:118-124)
            name = f"{client}-{manager}" if not manager.startswith(client) \
                else manager
        return name


class _HubHandler:
    """RPC surface; method names mirror HubConnectArgs/HubSyncArgs
    (rpctype.go:65-102)."""

    def __init__(self, hub: Hub):
        self._hub = hub

    def hub_connect(self, client: str, key: str, manager: str = "",
                    fresh: bool = False, calls: Sequence[str] = (),
                    corpus: Sequence[str] = ()):
        name = self._hub.auth(client, key, manager)
        with self._hub.lock:
            self._hub.state.connect(name, fresh, calls, corpus)
        return {}

    def hub_sync(self, client: str, key: str, manager: str = "",
                 need_repros: bool = False, repros: Sequence[str] = (),
                 add: Sequence[str] = (), **kw):
        name = self._hub.auth(client, key, manager)
        del_ = kw.get("del", kw.get("del_", []))
        with self._hub.lock:
            st = self._hub.state
            progs, more = st.sync(name, add, del_)
            for repro in repros:
                st.add_repro(name, repro)
            out_repros: List[str] = []
            if need_repros:
                r = st.pending_repro(name)
                if r is not None:
                    out_repros.append(r)
        return {"progs": progs, "more": more, "repros": out_repros}


class HubClient:
    """Manager-side connection to a hub (the manager's hubSync loop uses
    this; reference: syz-manager/manager.go:994-...)."""

    def __init__(self, addr: str, client: str, key: str, manager: str = ""):
        self._rpc = RpcClient(addr)
        self._ident = {"client": client, "key": key, "manager": manager}

    def connect(self, fresh: bool, calls: Sequence[str],
                corpus: Sequence[str]) -> None:
        self._rpc.call("hub_connect", fresh=fresh, calls=list(calls),
                       corpus=list(corpus), **self._ident)

    def sync(self, add: Sequence[str] = (), del_: Sequence[str] = (),
             repros: Sequence[str] = (), need_repros: bool = False):
        r = self._rpc.call("hub_sync", add=list(add),
                           repros=list(repros), need_repros=need_repros,
                           **{**self._ident, "del": list(del_)})
        return r["progs"], r["more"], r["repros"]

    def close(self) -> None:
        self._rpc.close()
