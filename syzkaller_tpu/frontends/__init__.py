"""Frontend registry: pluggable (target, executor) pairs for the engine.

The engine is a generic coverage-guided tensor-program fuzzer: fixed-width
integer program rows (prog/tensor.py), vmapped mutation (ops/mutation.py),
packed-bitset signal (ops/cover.py), device admission (ops/admission.py).
Nothing in that loop knows what a "syscall" is — a *frontend* supplies the
two domain-specific pieces:

    make_target(os, arch) -> prog.target.Target
        the op table ("syscalls"), resources, and arch hooks the codec,
        generator, and mutator compile into flat tables;
    make_env(target, pid, cfg) -> ipc.Env-compatible executor
        exec/exec_raw/exec_prefix/exec_suffix/close/restarts — the thing
        that turns an exec byte stream into per-call signal.

Built-in frontends:

    ``syscall`` — the original kernel-fuzzing frontend: bundled OS
        descriptions + the C++ in-VM executor (or MockEnv when
        ``cfg.mock``).  The default; the registry path is parity-pinned
        against the pre-registry construction by tests/test_frontends.py.
    ``hlo``     — StableHLO/XLA-style compiler fuzzing: ops are tensor
        operations, the executor is an in-process JAX compile+run harness
        with differential checking (frontends/hlo/).

Everything above the env boundary — arena, admission, prefix memoization,
supervision, checkpoint/resume, journal, fleet dashboard — is reused
unchanged across frontends; that reuse is pinned by tests.
"""

from __future__ import annotations

from typing import Dict, List

_registry: Dict[str, object] = {}


def register(frontend) -> None:
    """Register a frontend under ``frontend.name`` (last wins, so tests
    can shadow a built-in with an instrumented double)."""
    _registry[frontend.name] = frontend


def names() -> List[str]:
    """Registered frontend names, sorted — the CLI's rejection message
    and ``--frontend`` validation both quote this list."""
    return sorted(_registry)


def get(name: str):
    """Look up a frontend by name; unknown names raise KeyError carrying
    the full name list so callers can surface actionable errors."""
    if name not in _registry:
        raise KeyError(
            f"unknown frontend {name!r} (available: {', '.join(names())})")
    return _registry[name]


# Built-ins register at import time: the registry must be complete before
# any FuzzerConfig.frontend lookup or CLI validation runs.  The hlo
# frontend's executor imports jax lazily, so registering it here costs
# nothing on engines that never select it.
from . import syscall as _syscall  # noqa: E402
from . import hlo as _hlo  # noqa: E402

register(_syscall.SyscallFrontend())
register(_hlo.HloFrontend())
