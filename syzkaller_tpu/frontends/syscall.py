"""The ``syscall`` frontend: the original kernel-fuzzing configuration.

Target construction goes through ``prog.get_target`` (bundled OS
descriptions), env construction replicates the engine's historical loop
verbatim: ``MockEnv`` (hermetic, prefix-continuation-capable) when
``cfg.mock``, the real ``ipc.Env`` executor otherwise.  This file is the
parity anchor — tests/test_frontends.py pins that a campaign built
through this frontend is bit-identical to the pre-registry engine, so
the registry indirection can never drift for the default path.
"""

from __future__ import annotations

from ..ipc import Env, EnvConfig, MockEnv
from ..prog import get_target


class SyscallFrontend:
    name = "syscall"
    description = "kernel syscall fuzzing (bundled OS descriptions + ipc.Env)"

    def make_target(self, os: str = "linux", arch: str = "amd64"):
        return get_target(os, arch)

    def make_env(self, target, pid: int, cfg):
        if cfg.mock:
            return MockEnv(target, pid=pid,
                           prefix_cache_entries=cfg.prefix_cache_entries)
        ec = cfg.env_config or EnvConfig(sandbox=cfg.sandbox)
        return Env(target, pid=pid, config=ec)
