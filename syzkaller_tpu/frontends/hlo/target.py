"""The ``hlo/xla`` target: StableHLO/XLA-style tensor ops as "syscalls".

Each op is an ordinary ``prog.types.Syscall`` whose operands are typed
with the existing arg-type machinery — tensor operands are a resource
(``hlo_tensor``) threaded call-to-call exactly like an fd, dtype/shape
selectors are ``FlagsType`` enums over small dense tables, reduce axes
are ranged ints — so ``descriptions.tables.compile_tables`` flattens the
whole table into the same fixed-width slot templates the kernel-fuzzing
targets produce, and ``prog/tensor.py`` rows encode hlo programs with
**zero codec changes**.

The pass pipeline rides in the same row: the ``hlo_pass_*`` ops are
zero-operand markers whose presence anywhere in the program enables the
corresponding graph transform in the executor (frontends/hlo/passes.py).
Because passes are just calls, ``ops/mutation.py`` jointly mutates IR
and pass pipeline with zero kernel changes, and ``prog.mutation.minimize``
shrinks the pass list by the same call-removal ladder it uses for ops —
the Tzer joint IR+pass mutation story on unmodified machinery.

``hlo_setup`` is the mmap analogue: the engine's prelude/codec/prio
paths unconditionally consult ``target.mmap_syscall`` (the tensor codec
strips/reinserts it, the device pipeline masks it), so the target
supplies one even though the in-process executor has no address space
to prepare — it decodes as a no-op setup marker.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ...prog import prog as progmod
from ...prog.target import Target, register_target
from ...prog.types import (
    Dir,
    FlagsType,
    IntKind,
    IntType,
    LenType,
    ResourceDesc,
    ResourceType,
    Syscall,
    VmaType,
)

# ---- shared dtype / shape tables --------------------------------------
# Selector args index these by value (the executor reduces mod len, so a
# mutated selector always lands on a valid entry).  Small on purpose:
# the coverage space is (op, dtype, rank, pass) n-grams and every entry
# multiplies it.

DTYPES: Tuple[str, ...] = ("f32", "i32", "u32")
NP_DTYPES = (np.float32, np.int32, np.uint32)

SHAPES: Tuple[Tuple[int, ...], ...] = (
    (), (4,), (8,), (2, 3), (4, 4), (2, 2, 2), (1, 8), (3, 3),
)

MAX_RANK = max(len(s) for s in SHAPES)

# Pass markers: op name suffix -> bit in the executor's pass mask.
PASS_OPS: Tuple[str, ...] = ("fold", "cse", "dce", "reassoc", "fuse")

_TENSOR = ResourceDesc(
    name="hlo_tensor",
    typ=IntType(name="int64", size=8),
    kind=("hlo_tensor",),
    values=(0,),
)


def _tin(fname: str) -> ResourceType:
    return ResourceType(name="hlo_tensor", field_name=fname, size=8,
                        dir=Dir.IN, desc=_TENSOR)


_TOUT = ResourceType(name="hlo_tensor", size=8, dir=Dir.OUT, desc=_TENSOR)

_DTYPE = FlagsType(name="hlo_dtype", field_name="dtype", size=8,
                   vals=tuple(range(len(DTYPES))))
_SHAPE = FlagsType(name="hlo_shape", field_name="shape", size=8,
                   vals=tuple(range(len(SHAPES))))
_AXIS = IntType(name="hlo_axis", field_name="axis", size=8,
                kind=IntKind.RANGE, range_begin=0, range_end=MAX_RANK)
_VAL = IntType(name="hlo_val", field_name="val", size=8)

# (name, args, has_ret) — ids are dense list positions, nr == id (there
# is no kernel ABI to match; the exec wire carries the dense id).
_OP_SPECS = (
    ("hlo_setup",
     (VmaType(name="hlo_vma", field_name="addr", size=8,
              range_begin=1, range_end=1),
      LenType(name="len", field_name="len", size=8, buf="addr")),
     False),
    # leaves
    ("hlo_const", (_DTYPE, _SHAPE, _VAL), True),
    ("hlo_iota", (_DTYPE, _SHAPE), True),
    # elementwise unary
    ("hlo_neg", (_tin("t"),), True),
    ("hlo_abs", (_tin("t"),), True),
    ("hlo_tanh", (_tin("t"),), True),
    ("hlo_exp", (_tin("t"),), True),
    # elementwise binary
    ("hlo_add", (_tin("a"), _tin("b")), True),
    ("hlo_sub", (_tin("a"), _tin("b")), True),
    ("hlo_mul", (_tin("a"), _tin("b")), True),
    ("hlo_max", (_tin("a"), _tin("b")), True),
    ("hlo_min", (_tin("a"), _tin("b")), True),
    ("hlo_div", (_tin("a"), _tin("b")), True),
    # reductions
    ("hlo_reduce_sum", (_tin("t"), _AXIS), True),
    ("hlo_reduce_max", (_tin("t"), _AXIS), True),
    # contraction
    ("hlo_dot", (_tin("a"), _tin("b")), True),
    # shape ops
    ("hlo_reshape", (_tin("t"), _SHAPE), True),
    ("hlo_broadcast", (_tin("t"), _SHAPE), True),
    ("hlo_convert", (_tin("t"), _DTYPE), True),
    # control / selection
    ("hlo_select", (_tin("p"), _tin("a"), _tin("b")), True),
    ("hlo_clamp", (_tin("lo"), _tin("x"), _tin("hi")), True),
) + tuple(
    # pass-pipeline markers: zero-operand, no result — pure row payload
    (f"hlo_pass_{p}", (), False) for p in PASS_OPS
)


def build_target() -> Target:
    syscalls = [
        Syscall(id=i, nr=i, name=name, call_name=name, args=args,
                ret=_TOUT if has_ret else None)
        for i, (name, args, has_ret) in enumerate(_OP_SPECS)
    ]
    target = Target("hlo", "xla", page_size=4096, num_pages=16,
                    revision="hlo-1", syscalls=syscalls,
                    resources=[_TENSOR])
    _init_arch(target)
    return target


def _init_arch(target: Target) -> None:
    """Arch hooks mirroring descriptions/fuchsia: hlo_setup is the mmap
    analogue the codec prelude and device pipeline require."""
    mmap = target.syscall_map["hlo_setup"]

    def make_mmap(start: int, npages: int) -> progmod.Call:
        return progmod.Call(
            meta=mmap,
            args=[
                progmod.PointerArg(mmap.args[0], start, 0, npages, None),
                progmod.ConstArg(mmap.args[1], npages * target.page_size),
            ],
            ret=progmod.ReturnArg(None),
        )

    def analyze_mmap(c: progmod.Call):
        if c.meta.name == "hlo_setup":
            npages = c.args[1].val // target.page_size
            return c.args[0].page_index, npages, npages > 0
        return 0, 0, False

    target.mmap_syscall = mmap
    target.make_mmap = make_mmap
    target.analyze_mmap = analyze_mmap


_target: Optional[Target] = None


def ensure_registered() -> Target:
    """Build + register the hlo/xla target once per process (the prog
    registry rejects duplicates; the compiled-table cache keys on the
    target object, so everyone must share one instance)."""
    global _target
    if _target is None:
        _target = build_target()
        register_target(_target)
    return _target
