"""Graph-level "compiler passes" over decoded hlo node graphs.

Each pass is a semantics-preserving transform applied to the node graph
before the optimized (JAX-compiled) execution — the un-optimized numpy
reference always interprets the ORIGINAL graph, so any divergence a
pass introduces is a real differential finding.  Pass selection is the
bitmask of ``hlo_pass_*`` markers present in the program row, which is
what makes the pass pipeline co-mutate and co-minimize with the IR.

The transforms are deliberately modeled on the divergence surfaces real
tensor compilers expose (Tzer's joint IR+pass findings): constant
folding evaluates subgraphs at "compile time" with a different engine
than the runtime, CSE/DCE rewire and drop nodes, reassociation changes
float rounding within the comparator tolerance.
"""

from __future__ import annotations

from typing import Dict, List

from .target import PASS_OPS

# pass name -> bit position in the pass mask
PASS_BITS: Dict[str, int] = {name: 1 << i for i, name in enumerate(PASS_OPS)}

_BINARY_REASSOC = ("hlo_add", "hlo_mul")


def pass_mask(names) -> int:
    mask = 0
    for n in names:
        mask |= PASS_BITS.get(n, 0)
    return mask


def apply_passes(nodes: List, mask: int, evaluate) -> List:
    """Return a transformed copy of ``nodes`` under the enabled passes.

    ``evaluate(node, nodes)`` computes a node's value eagerly (numpy) —
    the const-folding "compile-time evaluator".  Nodes are the executor's
    ``Node`` records; transforms mutate copies, never the input list, so
    the reference interpreter still sees the original graph.
    """
    out = [n.clone() for n in nodes]
    if mask & PASS_BITS["fold"]:
        _fold(out, evaluate)
    if mask & PASS_BITS["cse"]:
        _cse(out)
    if mask & PASS_BITS["reassoc"]:
        _reassoc(out)
    if mask & PASS_BITS["dce"]:
        _dce(out)
    # "fuse" intentionally has no graph effect: it is a no-op marker that
    # still participates in coverage n-grams and seeded-bug triggers, so
    # campaigns explore pass *combinations* cheaply.
    return out


def _fold(nodes: List, evaluate) -> None:
    """Constant folding: a node whose operands are all literal leaves is
    evaluated now and replaced by a literal node."""
    for n in nodes:
        if n.op in ("hlo_const", "hlo_iota") or n.lit is not None:
            continue
        if n.srcs and all(nodes[s].lit is not None for s in n.srcs):
            try:
                n.lit = evaluate(n, nodes)
                n.folded = True
            except Exception:
                pass  # unfoldable (e.g. div by zero path): leave live


def _cse(nodes: List) -> None:
    """Common-subexpression elimination: structurally identical nodes
    collapse onto the first occurrence (consumers rewired)."""
    seen: Dict[tuple, int] = {}
    remap: Dict[int, int] = {}
    for n in nodes:
        srcs = tuple(remap.get(s, s) for s in n.srcs)
        n.srcs = list(srcs)
        key = n.structural_key()
        if key in seen:
            remap[n.idx] = seen[key]
        else:
            seen[key] = n.idx


def _reassoc(nodes: List) -> None:
    """Rotate (a ∘ b) ∘ c -> a ∘ (b ∘ c) for associative elementwise ops
    when both operands resolve to same-op chains — changes float rounding
    order (absorbed by the comparator tolerance) and exercises a rewrite
    the optimizer alone performs."""
    for n in nodes:
        if n.op not in _BINARY_REASSOC or len(n.srcs) != 2:
            continue
        left = nodes[n.srcs[0]]
        if left.op == n.op and len(left.srcs) == 2 and left.idx != n.idx:
            # (a op b) op c  ->  swap so the right subtree deepens; the
            # multiset of operands is unchanged
            a, b = left.srcs
            c = n.srcs[1]
            n.srcs = [a, c]
            n.reassoc_extra = b


def _dce(nodes: List) -> None:
    """Dead-code elimination: nodes unreachable from the graph outputs
    are marked dead (the executor skips evaluating them in the optimized
    run — a real effect once CSE has orphaned duplicates)."""
    live = set()
    stack = [n.idx for n in nodes if n.is_output]
    while stack:
        i = stack.pop()
        if i in live:
            continue
        live.add(i)
        n = nodes[i]
        stack.extend(n.srcs)
        if getattr(n, "reassoc_extra", None) is not None:
            stack.append(n.reassoc_extra)
    for n in nodes:
        if n.idx not in live:
            n.dead = True
