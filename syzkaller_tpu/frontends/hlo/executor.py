"""In-process differential executor for the hlo frontend.

``HloEnv`` is ``ipc.Env``-compatible (exec / exec_raw / exec_prefix /
exec_suffix / close / restarts), but instead of shipping a byte stream to
a forked C++ executor it:

  1. decodes the exec wire format to a tensor-op node graph (the exec
     stream's result-arg indices ARE the def-use edges — hlo programs
     are pointer-free, so instruction index == call index);
  2. statically infers every node's shape/dtype and the operand-coercion
     recipe (resize / cast / axis-mod), so the un-optimized reference and
     the optimized run execute THE SAME defined semantics — any
     divergence is the compiler's, not the harness's;
  3. applies the program's pass pipeline (frontends/hlo/passes.py, mask
     taken from the ``hlo_pass_*`` markers in the row), compiles the
     transformed graph with ``jax.jit`` under a structural-hash LRU
     compile cache, and runs it;
  4. interprets the ORIGINAL graph eagerly with numpy as the reference,
     and differentially compares outputs — miscompare / exception /
     timeout becomes a crash report through the existing manager crash
     path (``telemetry.journal_emit("crash", ...)`` — the exact call
     ``Manager.save_crash`` makes), attached as a distinctive crash PC
     on the trigger call so triage/minimize work unchanged;
  5. emits per-call coverage as hashed (op-kind, dtype, rank,
     pass-decision) n-gram PCs — plain ints the engine folds into the
     packed bitset via ``ops/cover.merge_and_new`` like any other signal.

Crashes are reported with ``failed=False``: the engine's execute() path
skips signal scanning for failed programs, and a miscompare is exactly
the signal we want triaged.  Env *death* (supervision testing) keeps the
``testing/faults.py`` ``env.exec:<pid>`` site contract from MockEnv.
"""

from __future__ import annotations

import hashlib
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...ipc import CallInfo, ExecOpts
from ...prog.encodingexec import decode_exec, serialize_for_exec
from ...prog.prog import Prog
from ...telemetry import get_registry, journal_emit, span
from ...testing import faults as _faults
from . import bugs as _bugs
from .passes import apply_passes, pass_mask
from .target import DTYPES, NP_DTYPES, SHAPES

COMPILE_CACHE_ENTRIES = 512
DEFAULT_TIMEOUT_S = 5.0

_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1
_U32_MAX = (1 << 32) - 1

_UNARY = {"hlo_neg", "hlo_abs", "hlo_tanh", "hlo_exp"}
_BINARY = {"hlo_add", "hlo_sub", "hlo_mul", "hlo_max", "hlo_min", "hlo_div"}
_REDUCE = {"hlo_reduce_sum", "hlo_reduce_max"}
_FLOAT_FORCED = {"hlo_tanh", "hlo_exp"}


def _pc(*parts) -> int:
    """Stable coverage PC: a 32-bit hash of the part tuple (hashlib, not
    hash() — PCs must agree across processes and PYTHONHASHSEED)."""
    h = hashlib.blake2b(repr(parts).encode(), digest_size=4)
    return int.from_bytes(h.digest(), "little")


class Node:
    """One decoded op instruction: the graph the passes transform and
    both interpreters evaluate.  ``lit`` non-None marks a literal leaf
    (const/iota values, plus fold results) — literal ARRAYS are runtime
    inputs to the jitted function, so the compile cache keys on graph
    STRUCTURE, never on constant values."""

    __slots__ = ("idx", "op", "call_id", "dtype", "shape", "srcs", "axis",
                 "lit", "is_output", "dead", "folded", "reassoc_extra")

    def __init__(self, idx: int, op: str, dtype: int = 0,
                 shape: Tuple[int, ...] = (), srcs=None, axis: int = 0):
        self.idx = idx
        self.op = op
        self.call_id = 0            # wire syscall id (for CallInfo.num)
        self.dtype = dtype          # index into DTYPES
        self.shape = shape          # inferred static shape
        self.srcs = list(srcs or [])
        self.axis = axis
        self.lit: Optional[np.ndarray] = None
        self.is_output = False
        self.dead = False
        self.folded = False
        self.reassoc_extra: Optional[int] = None

    def clone(self) -> "Node":
        n = Node(self.idx, self.op, self.dtype, self.shape,
                 list(self.srcs), self.axis)
        n.call_id = self.call_id
        n.lit = self.lit
        n.is_output = self.is_output
        n.dead = self.dead
        n.folded = self.folded
        n.reassoc_extra = self.reassoc_extra
        return n

    def structural_key(self) -> tuple:
        lit_sig = (None if self.lit is None
                   else (self.lit.shape, str(self.lit.dtype)))
        return (self.op, self.dtype, self.shape, tuple(self.srcs),
                self.axis, lit_sig, self.reassoc_extra)

    @property
    def produces(self) -> bool:
        return self.op not in ("hlo_setup",) \
            and not self.op.startswith("hlo_pass_")


def _np_dtype(di: int):
    return NP_DTYPES[di % len(NP_DTYPES)]


def _is_float(di: int) -> bool:
    return DTYPES[di % len(DTYPES)] == "f32"


def _cast(x, di: int, xp):
    """Defined-semantics convert: NaN/Inf scrubbed and range clamped
    before float->int casts, so numpy and XLA agree where raw casts are
    implementation-defined."""
    dt = _np_dtype(di)
    if x.dtype == dt:
        return x
    if not _is_float(di) and np.issubdtype(x.dtype, np.floating):
        x = xp.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)
        lo, hi = (0, _U32_MAX) if dt is np.uint32 else (_I32_MIN, _I32_MAX)
        # float bounds: a Python int >= 2**31 would overflow jax's x32
        # weak-typing before the clip even runs
        x = xp.clip(x, float(lo), float(hi))
    return x.astype(dt)


def _coerce(x, shape: Tuple[int, ...], di: int, xp):
    """Coerce an operand to the consumer's static (shape, dtype): scalars
    broadcast, anything else is cycled through ``resize`` — one rule,
    applied identically by the reference and the optimized run."""
    x = _cast(x, di, xp)
    if tuple(x.shape) == tuple(shape):
        return x
    if x.ndim == 0:
        return xp.broadcast_to(x, shape)
    return xp.resize(x, shape)


class _Graph:
    """A decoded program: node list + the pass mask its markers enable."""

    def __init__(self, nodes: List[Node], mask: int, op_names: List[str],
                 pass_names: List[str]):
        self.nodes = nodes
        self.mask = mask
        self.op_names = op_names
        self.pass_names = pass_names

    def outputs(self) -> List[Node]:
        return [n for n in self.nodes if n.is_output]


def _iota_lit(di: int, shape: Tuple[int, ...]) -> np.ndarray:
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return np.arange(n, dtype=_np_dtype(di)).reshape(shape)


def _const_lit(di: int, shape: Tuple[int, ...], val: int) -> np.ndarray:
    # canonicalize the raw 64-bit row value into something every dtype
    # represents exactly (so encode/decode round trips can't drift)
    v = int(val) % 256
    return np.full(shape, v, dtype=_np_dtype(di))


def build_graph(instrs, id_to_name: Dict[int, str]) -> _Graph:
    """Decoded exec stream -> node graph with static shape/dtype
    inference.  Result-arg indices point at instruction positions; a
    reference to a non-producing instruction (setup, pass marker,
    out-of-range after mutation) falls back to a literal zero scalar —
    every syntactically valid row is executable."""
    nodes: List[Node] = []
    op_names: List[str] = []
    pass_names: List[str] = []

    def src_of(arg) -> int:
        if arg["kind"] == "result":
            i = int(arg["index"])
            if 0 <= i < len(nodes) and nodes[i].produces:
                return i
        return -1

    for ins in instrs:
        if ins["op"] != "call":
            continue
        i = len(nodes)
        name = id_to_name.get(ins["id"], f"hlo_unknown_{ins['id']}")
        op_names.append(name)
        args = ins["args"]

        def cval(k: int, default: int = 0) -> int:
            if k < len(args) and args[k]["kind"] == "const":
                return int(args[k]["value"])
            return default

        n = Node(i, name)
        n.call_id = int(ins["id"])
        if name.startswith("hlo_pass_"):
            pass_names.append(name)
        elif name == "hlo_const":
            n.dtype = cval(0) % len(DTYPES)
            n.shape = SHAPES[cval(1) % len(SHAPES)]
            n.lit = _const_lit(n.dtype, n.shape, cval(2))
        elif name == "hlo_iota":
            n.dtype = cval(0) % len(DTYPES)
            n.shape = SHAPES[cval(1) % len(SHAPES)]
            n.lit = _iota_lit(n.dtype, n.shape)
        elif name in _UNARY:
            s = src_of(args[0]) if args else -1
            n.srcs = [s]
            base = nodes[s] if s >= 0 else None
            n.shape = base.shape if base else ()
            n.dtype = 0 if name in _FLOAT_FORCED else (
                base.dtype if base else 0)
        elif name in _BINARY:
            a = src_of(args[0]) if args else -1
            b = src_of(args[1]) if len(args) > 1 else -1
            n.srcs = [a, b]
            base = nodes[a] if a >= 0 else None
            n.shape = base.shape if base else ()
            n.dtype = base.dtype if base else 0
        elif name in _REDUCE:
            s = src_of(args[0]) if args else -1
            n.srcs = [s]
            base = nodes[s] if s >= 0 else None
            rank = len(base.shape) if base else 0
            n.axis = cval(1) % rank if rank else 0
            n.dtype = base.dtype if base else 0
            n.shape = (tuple(d for k, d in enumerate(base.shape)
                             if k != n.axis) if base else ())
        elif name == "hlo_dot":
            a = src_of(args[0]) if args else -1
            b = src_of(args[1]) if len(args) > 1 else -1
            n.srcs = [a, b]
            n.dtype = nodes[a].dtype if a >= 0 else 0
            n.shape = ()
        elif name in ("hlo_reshape", "hlo_broadcast"):
            s = src_of(args[0]) if args else -1
            n.srcs = [s]
            n.dtype = nodes[s].dtype if s >= 0 else 0
            n.shape = SHAPES[cval(1) % len(SHAPES)]
        elif name == "hlo_convert":
            s = src_of(args[0]) if args else -1
            n.srcs = [s]
            n.dtype = cval(1) % len(DTYPES)
            n.shape = nodes[s].shape if s >= 0 else ()
        elif name == "hlo_select":
            srcs = [src_of(a) for a in args[:3]]
            srcs += [-1] * (3 - len(srcs))
            n.srcs = srcs
            base = nodes[srcs[1]] if srcs[1] >= 0 else None
            n.shape = base.shape if base else ()
            n.dtype = base.dtype if base else 0
        elif name == "hlo_clamp":
            srcs = [src_of(a) for a in args[:3]]
            srcs += [-1] * (3 - len(srcs))
            n.srcs = srcs
            base = nodes[srcs[1]] if srcs[1] >= 0 else None
            n.shape = base.shape if base else ()
            n.dtype = base.dtype if base else 0
        # hlo_setup / unknown ids: non-producing marker node
        nodes.append(n)

    consumed = set()
    for n in nodes:
        for s in n.srcs:
            if s >= 0:
                consumed.add(s)
    for n in nodes:
        n.is_output = n.produces and n.idx not in consumed
    return _Graph(nodes, pass_mask(pass_names), op_names, pass_names)


def _eval(node: Node, nodes: List[Node], memo: Dict[int, object], xp,
          lits: Optional[Dict[int, object]] = None):
    """The one evaluator: interprets a node against ``xp`` (numpy for the
    eager reference, jax.numpy inside the jitted optimized function).
    ``lits`` overrides literal leaves with runtime-supplied arrays (the
    jit path), keeping constants out of the compiled artifact."""
    if node.idx in memo:
        return memo[node.idx]

    def val(i: int):
        if i < 0:
            return xp.zeros((), dtype=np.float32)
        return _eval(nodes[i], nodes, memo, xp, lits)

    if lits is not None and node.idx in lits:
        r = lits[node.idx]
    elif node.lit is not None:
        r = xp.asarray(node.lit)
    else:
        op, sh, dt = node.op, node.shape, node.dtype
        if op in _UNARY:
            x = _coerce(val(node.srcs[0]), sh, dt, xp)
            if op == "hlo_neg":
                r = -x
            elif op == "hlo_abs":
                r = xp.abs(x)
            elif op == "hlo_tanh":
                r = xp.tanh(x)
            else:
                r = xp.exp(x)
        elif op in _BINARY:
            a = _coerce(val(node.srcs[0]), sh, dt, xp)
            b = _coerce(val(node.srcs[1]), sh, dt, xp)
            r = _binop(op, a, b, dt, xp)
            if node.reassoc_extra is not None:
                c = _coerce(val(node.reassoc_extra), sh, dt, xp)
                r = _binop(op, r, c, dt, xp)
        elif op in _REDUCE:
            x = val(node.srcs[0])
            x = _cast(x, dt, xp)
            if x.ndim == 0:
                r = x
            else:
                ax = node.axis % x.ndim
                if op == "hlo_reduce_sum":
                    r = xp.sum(x, axis=ax, dtype=x.dtype)
                else:
                    r = xp.max(x, axis=ax)
        elif op == "hlo_dot":
            a = _cast(val(node.srcs[0]), dt, xp).reshape(-1)
            b = _cast(val(node.srcs[1]), dt, xp).reshape(-1)
            m = max(int(a.shape[0]), int(b.shape[0]), 1)
            a = xp.resize(a, (m,))
            b = xp.resize(b, (m,))
            r = xp.sum(a * b, dtype=a.dtype)
        elif op in ("hlo_reshape", "hlo_broadcast"):
            r = _coerce(val(node.srcs[0]), sh, dt, xp)
        elif op == "hlo_convert":
            r = _coerce(val(node.srcs[0]), sh, dt, xp)
        elif op == "hlo_select":
            p = _coerce(val(node.srcs[0]), sh, dt, xp)
            a = _coerce(val(node.srcs[1]), sh, dt, xp)
            b = _coerce(val(node.srcs[2]), sh, dt, xp)
            r = xp.where(p != 0, a, b)
        elif op == "hlo_clamp":
            lo = _coerce(val(node.srcs[0]), sh, dt, xp)
            x = _coerce(val(node.srcs[1]), sh, dt, xp)
            hi = _coerce(val(node.srcs[2]), sh, dt, xp)
            r = xp.minimum(xp.maximum(x, lo), hi)
        else:
            # setup / pass markers / unknown: inert zero scalar
            r = xp.zeros((), dtype=np.float32)
    memo[node.idx] = r
    return r


def _binop(op: str, a, b, dt: int, xp):
    if op == "hlo_add":
        return a + b
    if op == "hlo_sub":
        return a - b
    if op == "hlo_mul":
        return a * b
    if op == "hlo_max":
        return xp.maximum(a, b)
    if op == "hlo_min":
        return xp.minimum(a, b)
    # safe-div: integer denominators of 0 are defined as 1 (both engines
    # apply the same rule, so the op has ONE semantics, not UB)
    if not _is_float(dt):
        b = xp.where(b == 0, xp.ones_like(b), b)
        return (a // b).astype(a.dtype)
    return a / b


class HloEnv:
    """ipc.Env-compatible in-process JAX compile+run differential
    executor.  One per engine proc, like every other env; the compile
    cache is per-env so restarts reset it the way a real executor
    respawn drops its JIT state."""

    supports_continuation = False

    def __init__(self, target, pid: int = 0,
                 compile_cache_entries: int = COMPILE_CACHE_ENTRIES,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self.target = target
        self.pid = pid
        self.restarts = 0
        self.timeout_s = timeout_s
        self.compile_cache_entries = max(int(compile_cache_entries), 1)
        self._compile_cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._id_to_name = {c.id: c.name for c in target.syscalls}
        self._crash_titles = set()

        reg = get_registry()
        self._c_compiles = reg.counter(
            "frontend_compiles_total",
            help="hlo frontend: jit compilations (compile-cache misses)")
        self._c_cache_hits = reg.counter(
            "frontend_compile_cache_hits_total",
            help="hlo frontend: structural compile-cache hits")
        self._c_miscompares = reg.counter(
            "frontend_miscompares_total",
            help="hlo frontend: differential miscompares reported")
        self._c_exceptions = reg.counter(
            "frontend_exceptions_total",
            help="hlo frontend: compile/run exceptions reported")
        self._c_timeouts = reg.counter(
            "frontend_exec_timeouts_total",
            help="hlo frontend: compile+run deadline overruns reported")
        self._h_compile = reg.histogram(
            "frontend_compile_seconds",
            help="hlo frontend: jit compile latency")
        self._h_run = reg.histogram(
            "frontend_run_seconds",
            help="hlo frontend: optimized-run + reference latency")

    # ---- env plumbing ------------------------------------------------

    def close(self) -> None:
        self._compile_cache.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def exec(self, opts: ExecOpts, p: Prog
             ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        data = serialize_for_exec(p, pid=self.pid)
        return self.exec_raw(opts, data, [c.meta.id for c in p.calls])

    def exec_prefix(self, opts: ExecOpts, data: bytes,
                    call_ids: List[int]) -> None:
        # no continuation support: returns None so the drain scheduler
        # never pays a wasted round trip (same contract as ipc.Env)
        return None

    def exec_suffix(self, opts: ExecOpts, data: bytes, call_ids,
                    prefix_hash: int, prefix_calls: int):
        out, infos, failed, hanged = self.exec_raw(opts, data, call_ids)
        return out, infos, failed, hanged, False

    # ---- the differential harness ------------------------------------

    def exec_raw(self, opts: ExecOpts, data: bytes, call_ids: List[int]
                 ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        if _faults.should_fire(f"env.exec:{self.pid}"):
            # injected env death: report failed like a crashed executor
            # (the drain supervisor path is frontend-agnostic)
            self.restarts += 1
            return b"", [], True, False

        budget = self.timeout_s
        if getattr(opts, "timeout_ms", 0):
            budget = min(budget, opts.timeout_ms / 1000.0)
        t0 = time.perf_counter()

        graph = build_graph(decode_exec(data), self._id_to_name)
        infos = self._cover_infos(opts, graph)
        plan = _bugs.active()
        matches = plan.match(graph.op_names, graph.pass_names) if plan \
            else []

        try:
            ref, opt, timed_out = self._run_differential(graph, matches,
                                                         plan, t0, budget)
        except Exception as e:  # compiler raised: that IS the finding
            idx = len(graph.nodes) - 1 if graph.nodes else 0
            title = f"hlo-exception-{type(e).__name__}"
            for b in matches:
                if b.kind == "exception":
                    idx = self._trigger_idx(graph, b)
                    title = f"hlo-seeded-{b.name}"
                    if plan:
                        plan.record(b, idx)
                    break
            self._crash(opts, infos, idx, title, self._c_exceptions)
            return b"", infos, False, False

        if timed_out is not None:
            self._crash(opts, infos, timed_out, "hlo-timeout",
                        self._c_timeouts)
            return b"", infos, False, False

        self._compare(opts, graph, infos, ref, opt, matches, plan)
        return b"", infos, False, False

    def _run_differential(self, graph: _Graph, matches, plan, t0: float,
                          budget: float):
        """Reference-interpret the original graph (numpy, eager) and
        compile+run the pass-transformed graph (jax); returns
        (ref_outputs, opt_outputs, timeout_trigger_idx_or_None)."""
        outputs = graph.outputs()
        with np.errstate(all="ignore"):
            memo: Dict[int, object] = {}
            ref = {n.idx: np.asarray(_eval(n, graph.nodes, memo, np))
                   for n in outputs}

        for b in matches:
            if b.kind == "exception":
                raise RuntimeError(f"seeded compiler crash {b.name}")

        opt = self._run_optimized(graph, outputs)

        elapsed = time.perf_counter() - t0
        for b in matches:
            if b.kind == "timeout":
                idx = self._trigger_idx(graph, b)
                if plan:
                    plan.record(b, idx)
                return ref, opt, idx
        if elapsed > budget:
            return ref, opt, len(graph.nodes) - 1 if graph.nodes else 0
        return ref, opt, None

    def _run_optimized(self, graph: _Graph, outputs: List[Node]):
        """Pass-transform, jit-compile (structural cache), run."""
        import jax

        def eager(node, nodes):
            # const-fold evaluator: the "compile-time" engine
            with np.errstate(all="ignore"):
                return np.asarray(_eval(node, nodes, {}, np))

        tnodes = apply_passes(graph.nodes, graph.mask, eager)
        out_idx = [n.idx for n in outputs]
        lit_idx = [n.idx for n in tnodes if n.lit is not None]
        key = (tuple(n.structural_key() for n in tnodes),
               tuple(out_idx), tuple(lit_idx))

        lit_vals = tuple(tnodes[i].lit for i in lit_idx)
        fn = self._compile_cache.get(key)
        if fn is not None:
            self._compile_cache.move_to_end(key)
            self._c_cache_hits.inc()
        else:
            import jax.numpy as jnp

            def run(lvals):
                lits = dict(zip(lit_idx, lvals))
                memo: Dict[int, object] = {}
                return tuple(_eval(tnodes[i], tnodes, memo, jnp, lits)
                             for i in out_idx)

            # AOT lower+compile (jax.jit alone defers compilation to the
            # first call, which would book compile time as run time and
            # make the cache-hit metric meaningless)
            with span("frontend.compile"):
                tc = time.perf_counter()
                fn = jax.jit(run).lower(lit_vals).compile()
                self._h_compile.observe(time.perf_counter() - tc)
            self._c_compiles.inc()
            self._compile_cache[key] = fn
            while len(self._compile_cache) > self.compile_cache_entries:
                self._compile_cache.popitem(last=False)
        with span("frontend.run"):
            tr = time.perf_counter()
            res = fn(lit_vals)
            res = tuple(np.asarray(r) for r in res)  # block + host copy
            self._h_run.observe(time.perf_counter() - tr)
        return dict(zip(out_idx, res))

    def _compare(self, opts, graph, infos, ref, opt, matches, plan):
        """Differential check + seeded-miscompare injection."""
        for b in matches:
            if b.kind == "miscompare":
                idx = self._trigger_idx(graph, b)
                if plan:
                    plan.record(b, idx)
                self._crash(opts, infos, idx, f"hlo-seeded-{b.name}",
                            self._c_miscompares)
                return
        for i, r in ref.items():
            o = opt.get(i)
            if o is None:
                continue
            if not self._agree(r, o):
                self._crash(opts, infos, i,
                            f"hlo-miscompare-{graph.nodes[i].op}",
                            self._c_miscompares)
                return

    @staticmethod
    def _agree(r: np.ndarray, o: np.ndarray) -> bool:
        if r.shape != o.shape:
            return False
        if np.issubdtype(r.dtype, np.floating) \
                or np.issubdtype(o.dtype, np.floating):
            return bool(np.allclose(
                r.astype(np.float64), o.astype(np.float64),
                rtol=1e-3, atol=1e-3, equal_nan=True))
        return bool(np.array_equal(r, o))

    @staticmethod
    def _trigger_idx(graph: _Graph, bug) -> int:
        for n in graph.nodes:
            if n.op == bug.op:
                return n.idx
        return 0

    def _crash(self, opts, infos: List[CallInfo], idx: int, title: str,
               counter) -> None:
        """Report through the existing manager crash path: the crash PC
        lands on the TRIGGER call's signal (stable under minimize's
        removal of unrelated calls), errno marks it, and the journal gets
        the same ``crash`` record ``Manager.save_crash`` writes."""
        counter.inc()
        if 0 <= idx < len(infos):
            infos[idx].errno = 5
            if opts.collect_signal:
                infos[idx].signal.append(_pc("bug", title))
            if opts.collect_cover:
                infos[idx].cover.append(_pc("bug", title))
        if title not in self._crash_titles:
            self._crash_titles.add(title)
            journal_emit("crash", title=title, vm=self.pid,
                         frontend="hlo")

    def _cover_infos(self, opts: ExecOpts, graph: _Graph
                     ) -> List[CallInfo]:
        """Per-call coverage: hashed (op, dtype, rank, pass-mask) n-gram
        PCs.  A pure function of the instruction stream, so triage's
        rerun-intersection keeps it (determinism is what makes the
        admission dedup and prefix machinery behave identically to the
        syscall frontend)."""
        mask = graph.mask
        infos: List[CallInfo] = []
        prev_op = ""
        for n in graph.nodes:
            if n.op == "hlo_setup":
                sig = [_pc("setup")]
            elif n.op.startswith("hlo_pass_"):
                sig = [_pc("pass", n.op, mask)]
            else:
                sig = [
                    _pc("op", n.op),
                    _pc("op", n.op, n.dtype, len(n.shape), mask),
                    _pc("2gram", prev_op, n.op, mask),
                ]
                prev_op = n.op
            infos.append(CallInfo(
                index=n.idx, num=n.call_id, errno=0,
                executed=True, fault_injected=False,
                signal=sig if opts.collect_signal else [],
                cover=list(sig) if opts.collect_cover else [],
                comps=[]))
        return infos
