"""Seeded compiler-bug harness for the hlo frontend.

The differential executor's correctness claim — miscompares / crashes /
hangs found, triaged, minimized, journaled — needs ground truth to test
against, the way ``testing/faults.py`` gives the supervision paths
deterministic chaos.  A ``BugPlan`` declares known-bad (op, pass)
combinations; the executor consults the installed plan per program and,
when a bug's trigger matches, manufactures the corresponding failure in
the OPTIMIZED run only:

    ``miscompare`` — the optimized output of the trigger op's node is
        perturbed, so the differential comparator reports it;
    ``exception``  — the "compiler" raises at the trigger node;
    ``timeout``    — the optimized run reports a deadline overrun.

Triggers are pure functions of program CONTENT (op present AND, when
``pass_name`` is set, that pass marker present) — never of occurrence
counts — so a seeded bug reproduces under triage's reruns and survives
exactly those minimization steps that keep both the trigger op and the
required pass marker.  That is what makes "minimize shrinks both the op
program and the pass list" a testable property: dropping either side of
the trigger makes the bug vanish, so the minimizer must keep both.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


@dataclass(frozen=True)
class SeededBug:
    """One known-bad (op, pass) combination.  ``kind`` selects the
    failure mode; ``pass_name`` == "" triggers on the op alone."""
    name: str
    op: str
    pass_name: str = ""
    kind: str = "miscompare"  # miscompare | exception | timeout


@dataclass
class BugPlan:
    """A set of seeded bugs plus a fired log (bug name, trigger op node
    index) so tests can assert exactly which bugs a campaign tickled."""
    bugs: Tuple[SeededBug, ...] = ()
    _fired: List[Tuple[str, int]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def match(self, op_names, pass_names) -> List[SeededBug]:
        """Bugs whose trigger is satisfied by this program's op multiset
        and pass marker set (content-only: deterministic under rerun)."""
        ops = set(op_names)
        passes = set(pass_names)
        return [b for b in self.bugs
                if b.op in ops and (not b.pass_name or b.pass_name in passes)]

    def record(self, bug: SeededBug, node_idx: int) -> None:
        with self._lock:
            self._fired.append((bug.name, node_idx))

    def fired(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._fired)

    def fired_names(self):
        return {name for name, _ in self.fired()}


_active: Optional[BugPlan] = None


def install(plan: Optional[BugPlan]) -> None:
    """Make ``plan`` the process-wide seeded-bug plan (None to disarm).
    No plan installed -> the executor's consult hook is one global read."""
    global _active
    _active = plan


def clear() -> None:
    install(None)


def active() -> Optional[BugPlan]:
    return _active


def default_plan() -> BugPlan:
    """The stock seeded-bug set used by the e2e test and bench harness:
    one bug per failure mode, each requiring an op AND a pass marker so
    minimization provably has to keep both."""
    return BugPlan(bugs=(
        SeededBug(name="fold-dot-miscompare", op="hlo_dot",
                  pass_name="hlo_pass_fold", kind="miscompare"),
        SeededBug(name="cse-tanh-miscompare", op="hlo_tanh",
                  pass_name="hlo_pass_cse", kind="miscompare"),
        SeededBug(name="fuse-convert-crash", op="hlo_convert",
                  pass_name="hlo_pass_fuse", kind="exception"),
    ))
