"""The ``hlo`` frontend: fuzz the tensor compiler itself.

"Syscalls" are StableHLO/XLA-style ops (frontends/hlo/target.py), the
executor is an in-process JAX compile+run differential harness
(frontends/hlo/executor.py), and the pass pipeline rides in the same
fixed-width program row as the IR so mutation and minimization treat
both jointly.  Everything above the env boundary is the stock engine.
"""

from __future__ import annotations

from . import target as _target


class HloFrontend:
    name = "hlo"
    description = ("XLA/StableHLO compiler fuzzing: in-process JAX "
                   "differential executor")

    def make_target(self, os: str = "hlo", arch: str = "xla"):
        # os/arch args are accepted for factory-signature parity with the
        # syscall frontend; there is exactly one hlo target.
        return _target.ensure_registered()

    def make_env(self, target, pid: int, cfg):
        from .executor import HloEnv

        return HloEnv(target, pid=pid)
