"""x86 machine-code generation/mutation for `text` buffer args.

Role parity with reference /root/reference/pkg/ifuzz (ifuzz.go:9-40
Generate/Mutate/Decode over an instruction table; the reference's table is
generated from Intel XED).  This implementation is original: a hand-curated
table of ~120 encodings chosen for kernel-interest (privileged ops, mode
switches, MSR/CR access, interrupts, string ops, branches) plus a compact
encoder — enough to synthesize plausible guest code for KVM fuzzing
(`syz_kvm_setup_cpu` payloads) and `text[x86_64]` args.

Layout note for the device path: `table_rows()` exports the table as
fixed-width numpy template rows (template bytes, length, imm offset/size)
that ops/textgen.py turns into a vectorized TPU batch generator.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

# modes (reference ifuzz.go:16-22)
MODE_LONG64 = 0
MODE_PROT32 = 1
MODE_PROT16 = 2
MODE_REAL16 = 3
MODE_LAST = 4

_ALL = (1 << MODE_LAST) - 1
_32_PLUS = (1 << MODE_LONG64) | (1 << MODE_PROT32)
_LEGACY = (1 << MODE_PROT32) | (1 << MODE_PROT16) | (1 << MODE_REAL16)


@dataclass(frozen=True)
class Insn:
    name: str
    opcode: bytes
    mode: int = _ALL          # bitmask of compatible modes
    modrm: bool = False       # needs a ModRM byte
    imm: int = 0              # immediate bytes (-1: operand-size 2/4)
    priv: bool = False        # CPL0-only
    rexw: int = 0             # 1: REX.W required (long mode only)
    fixed_modrm: int = -1     # >=0: the encoder must use exactly this ModRM


def _i(name, opcode, **kw) -> Insn:
    return Insn(name=name, opcode=bytes(opcode), **kw)


# Curated instruction table.  Unprivileged first, privileged at the end.
INSNS: List[Insn] = [
    # one-byte no-operand
    _i("nop", [0x90]),
    _i("cwde", [0x98]),
    _i("cdq", [0x99]),
    _i("sahf", [0x9E]),
    _i("lahf", [0x9F]),
    _i("ret", [0xC3]),
    _i("leave", [0xC9]),
    _i("int3", [0xCC]),
    _i("into", [0xCE], mode=_LEGACY),
    _i("iret", [0xCF]),
    _i("cmc", [0xF5]),
    _i("clc", [0xF8]),
    _i("stc", [0xF9]),
    _i("cld", [0xFC]),
    _i("std", [0xFD]),
    _i("pusha", [0x60], mode=_LEGACY),
    _i("popa", [0x61], mode=_LEGACY),
    _i("pushf", [0x9C]),
    _i("popf", [0x9D]),
    _i("xlat", [0xD7]),
    _i("ud2", [0x0F, 0x0B]),
    _i("cpuid", [0x0F, 0xA2]),
    _i("rdtsc", [0x0F, 0x31]),
    _i("emms", [0x0F, 0x77]),
    # push/pop register (register embedded in opcode)
    *[_i(f"push_r{r}", [0x50 + r]) for r in range(8)],
    *[_i(f"pop_r{r}", [0x58 + r]) for r in range(8)],
    # immediates
    _i("push_imm8", [0x6A], imm=1),
    _i("push_imm", [0x68], imm=-1),
    _i("int_imm8", [0xCD], imm=1),
    _i("ret_imm16", [0xC2], imm=2),
    _i("mov_al_imm8", [0xB0], imm=1),
    _i("mov_eax_imm", [0xB8], imm=-1),
    _i("add_al_imm8", [0x04], imm=1),
    _i("add_eax_imm", [0x05], imm=-1),
    _i("sub_al_imm8", [0x2C], imm=1),
    _i("sub_eax_imm", [0x2D], imm=-1),
    _i("and_al_imm8", [0x24], imm=1),
    _i("or_al_imm8", [0x0C], imm=1),
    _i("xor_al_imm8", [0x34], imm=1),
    _i("cmp_al_imm8", [0x3C], imm=1),
    _i("cmp_eax_imm", [0x3D], imm=-1),
    _i("test_al_imm8", [0xA8], imm=1),
    _i("test_eax_imm", [0xA9], imm=-1),
    _i("in_al_imm8", [0xE4], imm=1, priv=True),
    _i("in_eax_imm8", [0xE5], imm=1, priv=True),
    _i("out_imm8_al", [0xE6], imm=1, priv=True),
    _i("out_imm8_eax", [0xE7], imm=1, priv=True),
    _i("in_al_dx", [0xEC], priv=True),
    _i("out_dx_al", [0xEE], priv=True),
    # short branches
    *[_i(f"j{cc:x}_rel8", [0x70 + cc], imm=1) for cc in range(16)],
    _i("jmp_rel8", [0xEB], imm=1),
    _i("jmp_rel", [0xE9], imm=-1),
    _i("call_rel", [0xE8], imm=-1),
    _i("loop", [0xE2], imm=1),
    _i("loope", [0xE1], imm=1),
    _i("loopne", [0xE0], imm=1),
    _i("jcxz", [0xE3], imm=1),
    # string ops (with/without rep handled by prefix sampling)
    _i("movsb", [0xA4]),
    _i("movs", [0xA5]),
    _i("stosb", [0xAA]),
    _i("stos", [0xAB]),
    _i("lodsb", [0xAC]),
    _i("lods", [0xAD]),
    _i("scasb", [0xAE]),
    _i("scas", [0xAF]),
    _i("cmpsb", [0xA6]),
    _i("cmps", [0xA7]),
    _i("insb", [0x6C], priv=True),
    _i("ins", [0x6D], priv=True),
    _i("outsb", [0x6E], priv=True),
    _i("outs", [0x6F], priv=True),
    # modrm r/m forms
    _i("add_rm_r", [0x01], modrm=True),
    _i("add_r_rm", [0x03], modrm=True),
    _i("or_rm_r", [0x09], modrm=True),
    _i("and_rm_r", [0x21], modrm=True),
    _i("sub_rm_r", [0x29], modrm=True),
    _i("xor_rm_r", [0x31], modrm=True),
    _i("cmp_rm_r", [0x39], modrm=True),
    _i("mov_rm_r", [0x89], modrm=True),
    _i("mov_r_rm", [0x8B], modrm=True),
    _i("test_rm_r", [0x85], modrm=True),
    _i("xchg_rm_r", [0x87], modrm=True),
    _i("lea", [0x8D], modrm=True),
    _i("mov_rm_imm", [0xC7], modrm=True, imm=-1),
    _i("mov_rm8_imm8", [0xC6], modrm=True, imm=1),
    _i("grp1_rm_imm8", [0x83], modrm=True, imm=1),
    _i("grp1_rm_imm", [0x81], modrm=True, imm=-1),
    _i("shift_rm_1", [0xD1], modrm=True),
    _i("shift_rm_cl", [0xD3], modrm=True),
    _i("shift_rm_imm8", [0xC1], modrm=True, imm=1),
    _i("inc_dec_rm", [0xFF], modrm=True),
    _i("neg_not_rm", [0xF7], modrm=True),
    _i("movzx_r_rm8", [0x0F, 0xB6], modrm=True),
    _i("movsx_r_rm8", [0x0F, 0xBE], modrm=True),
    _i("imul_r_rm", [0x0F, 0xAF], modrm=True),
    _i("bt_rm_r", [0x0F, 0xA3], modrm=True),
    _i("bts_rm_r", [0x0F, 0xAB], modrm=True),
    _i("bsf_r_rm", [0x0F, 0xBC], modrm=True),
    _i("setcc_rm8", [0x0F, 0x94], modrm=True),
    _i("cmovz_r_rm", [0x0F, 0x44], modrm=True),
    _i("jcc_rel", [0x0F, 0x84], imm=-1),
    _i("xadd_rm_r", [0x0F, 0xC1], modrm=True),
    _i("cmpxchg_rm_r", [0x0F, 0xB1], modrm=True),
    # system / privileged (the interesting ones for KVM fuzzing)
    _i("syscall", [0x0F, 0x05], mode=1 << MODE_LONG64),
    _i("sysenter", [0x0F, 0x34], mode=_32_PLUS),
    _i("sysexit", [0x0F, 0x35], mode=_32_PLUS, priv=True),
    _i("sysret", [0x0F, 0x07], mode=1 << MODE_LONG64, priv=True),
    _i("hlt", [0xF4], priv=True),
    _i("cli", [0xFA], priv=True),
    _i("sti", [0xFB], priv=True),
    _i("clts", [0x0F, 0x06], priv=True),
    _i("invd", [0x0F, 0x08], priv=True),
    _i("wbinvd", [0x0F, 0x09], priv=True),
    _i("rdmsr", [0x0F, 0x32], priv=True),
    _i("wrmsr", [0x0F, 0x30], priv=True),
    _i("rdpmc", [0x0F, 0x33], priv=True),
    _i("rsm", [0x0F, 0xAA], priv=True),
    _i("mov_cr0_r", [0x0F, 0x22], priv=True, fixed_modrm=0xC0),
    _i("mov_r_cr0", [0x0F, 0x20], priv=True, fixed_modrm=0xC0),
    _i("mov_cr3_r", [0x0F, 0x22], priv=True, fixed_modrm=0xD8),
    _i("mov_r_cr3", [0x0F, 0x20], priv=True, fixed_modrm=0xD8),
    _i("mov_cr4_r", [0x0F, 0x22], priv=True, fixed_modrm=0xE0),
    _i("mov_dr_r", [0x0F, 0x23], priv=True, fixed_modrm=0xC0),
    _i("lmsw_r", [0x0F, 0x01], priv=True, fixed_modrm=0xF0),
    _i("smsw_r", [0x0F, 0x01], priv=True, fixed_modrm=0xE0),
    _i("sgdt", [0x0F, 0x01], modrm=True, fixed_modrm=0x00, priv=True),
    _i("sidt", [0x0F, 0x01], modrm=True, fixed_modrm=0x08, priv=True),
    _i("lgdt", [0x0F, 0x01], modrm=True, fixed_modrm=0x10, priv=True),
    _i("lidt", [0x0F, 0x01], modrm=True, fixed_modrm=0x18, priv=True),
    _i("invlpg", [0x0F, 0x01], modrm=True, fixed_modrm=0x38, priv=True),
    _i("vmcall", [0x0F, 0x01], fixed_modrm=0xC1, priv=True),
    _i("vmlaunch", [0x0F, 0x01], fixed_modrm=0xC2, priv=True),
    _i("vmresume", [0x0F, 0x01], fixed_modrm=0xC3, priv=True),
    _i("vmxoff", [0x0F, 0x01], fixed_modrm=0xC4, priv=True),
    _i("monitor", [0x0F, 0x01], fixed_modrm=0xC8, priv=True),
    _i("mwait", [0x0F, 0x01], fixed_modrm=0xC9, priv=True),
    _i("swapgs", [0x0F, 0x01], fixed_modrm=0xF8,
       mode=1 << MODE_LONG64, priv=True),
    _i("rdtscp", [0x0F, 0x01], fixed_modrm=0xF9),
    _i("ltr_r", [0x0F, 0x00], fixed_modrm=0xD8, priv=True),
    _i("str_r", [0x0F, 0x00], fixed_modrm=0xC8),
    _i("lldt_r", [0x0F, 0x00], fixed_modrm=0xD0, priv=True),
    _i("sldt_r", [0x0F, 0x00], fixed_modrm=0xC0),
]

_PREFIXES = bytes([0x66, 0x67, 0xF0, 0xF2, 0xF3, 0x2E, 0x36, 0x3E, 0x26])

_INTERESTING_IMM = [0, 1, 0x7F, 0x80, 0xFF, 0x100, 0x7FFF, 0x8000,
                    0xFFFF, 0x10000, 0x7FFFFFFF, 0x80000000, 0xFFFFFFFF]


@dataclass
class Config:
    """Reference ifuzz.Config (ifuzz.go:57-63)."""

    length: int = 10        # number of instructions
    mode: int = MODE_LONG64
    priv: bool = True       # allow CPL0 instructions
    exec_: bool = True      # unused hook for pseudo-ops parity


def mode_insns(cfg: Config) -> List[Insn]:
    return [i for i in INSNS
            if (i.mode >> cfg.mode) & 1 and (cfg.priv or not i.priv)]


def _imm_size(insn: Insn, cfg: Config) -> int:
    if insn.imm >= 0:
        return insn.imm
    # operand-size immediate: 4 in 32/64-bit modes, 2 in 16-bit modes
    return 4 if cfg.mode in (MODE_LONG64, MODE_PROT32) else 2


def _gen_imm(rng: random.Random, size: int) -> bytes:
    if rng.random() < 0.5:
        v = rng.choice(_INTERESTING_IMM)
    else:
        v = rng.getrandbits(size * 8)
    return (v & ((1 << (size * 8)) - 1)).to_bytes(size, "little")


def encode_insn(insn: Insn, cfg: Config, rng: random.Random) -> bytes:
    out = bytearray()
    # optional legacy prefixes (sparingly, like real code)
    while rng.random() < 0.12:
        out.append(rng.choice(_PREFIXES))
    if cfg.mode == MODE_LONG64 and (insn.rexw == 1 or rng.random() < 0.2):
        rex = 0x40 | (0x08 if insn.rexw == 1 or rng.random() < 0.5 else 0)
        rex |= rng.getrandbits(3)  # R/X/B extension bits
        out.append(rex)
    out += insn.opcode
    if insn.fixed_modrm >= 0:
        out.append(insn.fixed_modrm)
        if insn.modrm and (insn.fixed_modrm >> 6) == 0:
            # memory form mod=00: maybe disp (rm=101 -> disp32/16)
            if (insn.fixed_modrm & 7) == 5:
                out += _gen_imm(rng, 4 if cfg.mode != MODE_REAL16 else 2)
    elif insn.modrm:
        mod = rng.choice([0, 1, 2, 3])
        reg = rng.getrandbits(3)
        rm = rng.getrandbits(3)
        out.append((mod << 6) | (reg << 3) | rm)
        if mod != 3:
            if cfg.mode == MODE_REAL16 or cfg.mode == MODE_PROT16:
                if mod == 1:
                    out += _gen_imm(rng, 1)
                elif mod == 2 or (mod == 0 and rm == 6):
                    out += _gen_imm(rng, 2)
            else:
                sib_base5 = False
                if rm == 4:  # SIB
                    sib = rng.getrandbits(8)
                    out.append(sib)
                    # SIB base=101 with mod=00 implies a disp32
                    sib_base5 = (sib & 7) == 5
                if mod == 1:
                    out += _gen_imm(rng, 1)
                elif mod == 2 or (mod == 0 and (rm == 5 or sib_base5)):
                    out += _gen_imm(rng, 4)
    sz = _imm_size(insn, cfg)
    if sz:
        out += _gen_imm(rng, sz)
    return bytes(out)


def generate(cfg: Config, rng: Optional[random.Random] = None) -> bytes:
    """cfg.length instructions of mode-appropriate machine code
    (reference ifuzz.go:118-126)."""
    rng = rng or random.Random()
    pool = mode_insns(cfg)
    out = bytearray()
    for _ in range(cfg.length):
        out += encode_insn(rng.choice(pool), cfg, rng)
    return bytes(out)


def mutate(cfg: Config, text: bytes,
           rng: Optional[random.Random] = None) -> bytes:
    """Instruction-granular mutation (reference ifuzz.go:127-190): split
    into insns (greedy table decode, 1-byte fallback), then insert /
    remove / replace / byte-perturb."""
    rng = rng or random.Random()
    chunks = split(cfg, text)
    if not chunks:
        return generate(cfg, rng)
    for _ in range(rng.randint(1, 3)):
        op = rng.randrange(4)
        idx = rng.randrange(len(chunks))
        if op == 0:  # insert
            chunks.insert(idx, encode_insn(
                rng.choice(mode_insns(cfg)), cfg, rng))
        elif op == 1 and len(chunks) > 1:  # remove
            del chunks[idx]
        elif op == 2:  # replace
            chunks[idx] = encode_insn(rng.choice(mode_insns(cfg)), cfg, rng)
        else:  # byte perturbation inside one insn
            b = bytearray(chunks[idx])
            if b:
                pos = rng.randrange(len(b))
                b[pos] ^= 1 << rng.randrange(8)
                chunks[idx] = bytes(b)
    return b"".join(chunks)


def decode(cfg: Config, data: bytes) -> int:
    """Length of the instruction at data[0:], or -1 if not in our table
    (reference decode.go's role, against our own table)."""
    pos = 0
    n = len(data)
    while pos < n and data[pos] in _PREFIXES:
        pos += 1
    if cfg.mode == MODE_LONG64 and pos < n and 0x40 <= data[pos] <= 0x4F:
        pos += 1
    best = -1
    for insn in INSNS:
        if not (insn.mode >> cfg.mode) & 1:
            continue
        op = insn.opcode
        if data[pos:pos + len(op)] != op:
            continue
        p = pos + len(op)
        if insn.fixed_modrm >= 0:
            if p >= n or data[p] != insn.fixed_modrm:
                continue
            p += 1
            if insn.modrm and (insn.fixed_modrm >> 6) == 0 \
                    and (insn.fixed_modrm & 7) == 5:
                p += 4 if cfg.mode != MODE_REAL16 else 2
        elif insn.modrm:
            if p >= n:
                continue
            modrm = data[p]
            p += 1
            mod, rm = modrm >> 6, modrm & 7
            if cfg.mode in (MODE_REAL16, MODE_PROT16):
                if mod == 1:
                    p += 1
                elif mod == 2 or (mod == 0 and rm == 6):
                    p += 2
            else:
                sib_base5 = False
                if mod != 3 and rm == 4:
                    if p >= n:
                        continue
                    sib_base5 = (data[p] & 7) == 5
                    p += 1
                if mod == 1:
                    p += 1
                elif mod == 2 or (mod == 0 and (rm == 5 or sib_base5)):
                    p += 4
        p += _imm_size(insn, cfg)
        if p <= n and p > best:
            best = p
    return best


def split(cfg: Config, text: bytes) -> List[bytes]:
    chunks: List[bytes] = []
    pos = 0
    while pos < len(text):
        ln = decode(cfg, text[pos:])
        if ln <= 0:
            ln = 1
        chunks.append(text[pos:pos + ln])
        pos += ln
    return chunks


# ---------------------------------------------------------------------- #
# device export: fixed-width template rows for ops/textgen.py


def table_rows(cfg: Config, max_len: int = 16):
    """(templates [N, max_len] u8, lengths [N], imm_off [N], imm_size [N]):
    one deterministic encoding per table entry (mod=3 modrm, zero imm),
    with the imm window exposed so device lanes can randomize it."""
    import numpy as np

    rng = random.Random(0)
    rows, lens, ioff, isz = [], [], [], []
    for insn in mode_insns(cfg):
        enc = bytearray(insn.opcode)
        if insn.fixed_modrm >= 0:
            enc.append(insn.fixed_modrm)
            if insn.modrm and (insn.fixed_modrm >> 6) == 0 \
                    and (insn.fixed_modrm & 7) == 5:
                enc += b"\x00\x00\x00\x00"
        elif insn.modrm:
            enc.append(0xC0 | (rng.getrandbits(3) << 3) | rng.getrandbits(3))
        sz = _imm_size(insn, cfg)
        off = len(enc) if sz else 0
        enc += b"\x00" * sz
        if len(enc) > max_len:
            continue
        lens.append(len(enc))
        ioff.append(off)
        isz.append(sz)
        rows.append(bytes(enc) + b"\x00" * (max_len - len(enc)))
    return (np.frombuffer(b"".join(rows),
                          dtype=np.uint8).reshape(len(rows), max_len).copy(),
            np.asarray(lens, np.int32), np.asarray(ioff, np.int32),
            np.asarray(isz, np.int32))
