"""Corpus database: append-only compacting compressed key-value store.

Capability parity with reference /root/reference/pkg/db/db.go:25-120
(corpus.db): crash-safe appends, tombstone deletes, automatic compaction
when the dead-record ratio grows. The corpus *is* the fuzzer's checkpoint
(SURVEY.md §5 checkpoint/resume), so records must survive torn writes: each
record is length-prefixed + CRC'd and a truncated tail is dropped on open.

Format: 16-byte header `SYZTPUDB` + u32 version + u32 reserved, then
records: u8 op (0=save, 1=delete), u32 klen, u32 vlen, u32 crc32(payload),
key bytes, zlib(value) bytes.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, Iterator, Optional, Tuple

_MAGIC = b"SYZTPUDB"
_VERSION = 1
_HDR = struct.Struct("<8sII")
_REC = struct.Struct("<BIII")

OP_SAVE = 0
OP_DELETE = 1


class DB:
    """Open with `DB.open(path)`; mutate with save/delete; `flush()` fsyncs.
    `compact()` rewrites the log dropping dead records; it runs automatically
    on open when more than half the records are dead."""

    def __init__(self, path: str):
        self.path = path
        self.records: Dict[bytes, bytes] = {}
        self._file = None
        self._total = 0  # appended records since last compaction

    # ---- lifecycle ----

    @classmethod
    def open(cls, path: str) -> "DB":
        db = cls(path)
        fresh = not os.path.exists(path) or os.path.getsize(path) < _HDR.size
        if not fresh:
            db._read_log()
        if db._total > 2 * max(len(db.records), 1):
            db.compact()
        else:
            db._file = open(path, "ab")
            if fresh:
                db._file.write(_HDR.pack(_MAGIC, _VERSION, 0))
                db._file.flush()
        return db

    def close(self) -> None:
        if self._file:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- reads ----

    def get(self, key: bytes) -> Optional[bytes]:
        return self.records.get(key)

    def __contains__(self, key: bytes) -> bool:
        return key in self.records

    def __len__(self) -> int:
        return len(self.records)

    def items(self) -> Iterator[Tuple[bytes, bytes]]:
        return iter(self.records.items())

    # ---- writes ----

    def save(self, key: bytes, value: bytes) -> None:
        self.records[key] = value
        self._append(OP_SAVE, key, value)
        self._total += 1

    def delete(self, key: bytes) -> None:
        if key not in self.records:
            return
        del self.records[key]
        self._append(OP_DELETE, key, b"")
        self._total += 1

    def flush(self) -> None:
        if self._file:
            self._file.flush()
            os.fsync(self._file.fileno())

    def compact(self) -> None:
        """Rewrite the log with only live records (atomic rename)."""
        if self._file:
            self._file.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_HDR.pack(_MAGIC, _VERSION, 0))
            for k, v in self.records.items():
                f.write(self._encode(OP_SAVE, k, v))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._file = open(self.path, "ab")
        self._total = len(self.records)

    # ---- log I/O ----

    @staticmethod
    def _encode(op: int, key: bytes, value: bytes) -> bytes:
        blob = zlib.compress(value) if op == OP_SAVE else b""
        payload = key + blob
        return _REC.pack(op, len(key), len(blob),
                         zlib.crc32(payload) & 0xFFFFFFFF) + payload

    def _append(self, op: int, key: bytes, value: bytes) -> None:
        if self._file is None:
            self._file = open(self.path, "ab")
        self._file.write(self._encode(op, key, value))

    def _read_log(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        if len(data) < _HDR.size:
            return
        magic, version, _ = _HDR.unpack_from(data, 0)
        if magic != _MAGIC or version != _VERSION:
            raise ValueError(f"{self.path}: not a corpus db")
        pos = _HDR.size
        while pos + _REC.size <= len(data):
            op, klen, vlen, crc = _REC.unpack_from(data, pos)
            end = pos + _REC.size + klen + vlen
            if end > len(data):
                break  # torn tail from a crash mid-append: drop it
            payload = data[pos + _REC.size:end]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                break
            key, blob = payload[:klen], payload[klen:]
            if op == OP_SAVE:
                try:
                    self.records[key] = zlib.decompress(blob)
                except zlib.error:
                    break
            elif op == OP_DELETE:
                self.records.pop(key, None)
            else:
                break
            self._total += 1
            pos = end
