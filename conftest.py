# Root conftest: force tests onto a virtual 8-device CPU mesh so sharding
# logic is exercised hermetically (the real TPU is reserved for bench runs).
# Must run before jax is imported anywhere.
import os
import sys

# Force-override: the environment may pin JAX_PLATFORMS to a hardware
# backend (e.g. the axon TPU tunnel, whose sitecustomize registers the
# plugin unconditionally); tests must stay hermetic on the virtual CPU
# mesh, so update the jax config directly as well.
os.environ["JAX_PLATFORMS"] = "cpu"
# Run pallas kernels through the interpreter on the CPU test backend
# (ops/pallas_cover.py gates on this; production CPU falls back to jnp).
os.environ["SYZTPU_PALLAS_INTERPRET"] = "1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass  # host-only tests still run; ops tests importorskip jax

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
